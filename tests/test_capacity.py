"""Capacity-ledger tests (ISSUE 9): per-(epoch, tier) fold accounting
(create/fetch/delete, hardlink last-link semantics, shm→spill→delete
tier transitions, high watermarks, cleanup), the store-path hooks with
ambient epoch context, exact spill-volume accounting under the event
rate limit, the capacity.* gauges, spool roundtrip, and the
zero-overhead proof for the whole decision plane (no capacity/critical/
slo import, no ledger files, when the env gates are unset)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu.runtime import store as store_mod
from ray_shuffling_data_loader_tpu.telemetry import (
    capacity,
    events,
    metrics,
    trace,
)

_ENV = (
    "RSDL_METRICS", "RSDL_METRICS_DIR", "RSDL_OBS_PORT", "RSDL_TS",
    "RSDL_SHM_DIR", "RSDL_SPILL_DIR", "RSDL_EVENTS_DIR",
    "RSDL_STORE_CAPACITY_BYTES",
)


@pytest.fixture
def cap_env(tmp_path):
    """Metrics on, spooling to a per-test dir, ledger state reset —
    function-scoped per the obs test convention."""
    saved = {k: os.environ.get(k) for k in _ENV}
    spool = str(tmp_path / "metrics-spool")
    os.environ["RSDL_METRICS"] = "1"
    os.environ["RSDL_METRICS_DIR"] = spool
    for k in _ENV[2:]:
        os.environ.pop(k, None)
    metrics.refresh_from_env()
    metrics.reset()
    capacity.reset(clear_spool=True)
    events.reset()
    yield spool
    capacity.reset(clear_spool=True)
    events.reset()
    metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    metrics.refresh_from_env()


def _rec(op, rid, ts, nbytes=0, tier=None, epoch=None, ids=None):
    rec = {"ts": ts, "op": op, "id": rid, "pid": 1}
    if nbytes:
        rec["nbytes"] = nbytes
    if tier:
        rec["tier"] = tier
    if epoch is not None:
        rec["epoch"] = epoch
    if ids:
        rec["ids"] = ids
    return rec


def test_ledger_create_delete_accounting(cap_env):
    records = [
        _rec("create", "a", 1.0, nbytes=100, tier="shm", epoch=0),
        _rec("create", "b", 2.0, nbytes=200, tier="shm", epoch=1),
        _rec("fetch", "c", 3.0, nbytes=50, tier="spill", epoch=1),
        _rec("delete", "a", 4.0),
    ]
    folded = capacity.ledger(records, now=10.0)
    e0 = folded["epochs"]["0"]["shm"]
    assert e0["resident_bytes"] == 0
    assert e0["created_bytes"] == 100
    assert e0["freed_bytes"] == 100
    assert e0["hwm_bytes"] == 100
    e1_shm = folded["epochs"]["1"]["shm"]
    assert e1_shm["resident_bytes"] == 200
    assert e1_shm["segments"] == 1
    assert e1_shm["oldest_age_s"] == pytest.approx(8.0)
    e1_spill = folded["epochs"]["1"]["spill"]
    assert e1_spill["resident_bytes"] == 50
    assert e1_spill["fetched_bytes"] == 50
    assert folded["totals"]["shm"]["resident_bytes"] == 200
    assert folded["totals"]["spill"]["resident_bytes"] == 50
    assert folded["live_segments"] == 2


def test_ledger_tier_transition_shm_spill_delete(cap_env):
    """The satellite acceptance: a segment demoted shm→spill moves its
    bytes between tiers (a move, not a free — the evictor's op), and
    the final delete frees it from the tier it ended on."""
    records = [
        _rec("create", "a", 1.0, nbytes=100, tier="shm", epoch=2),
        _rec("transition", "a", 2.0, tier="spill"),
    ]
    folded = capacity.ledger(records, now=3.0)
    shm = folded["epochs"]["2"]["shm"]
    spill = folded["epochs"]["2"]["spill"]
    assert shm["resident_bytes"] == 0 and shm["segments"] == 0
    assert shm["freed_bytes"] == 0  # moved, not freed
    assert shm["hwm_bytes"] == 100  # it WAS resident in shm
    assert spill["resident_bytes"] == 100 and spill["segments"] == 1
    assert spill["hwm_bytes"] == 100

    records.append(_rec("delete", "a", 4.0))
    folded = capacity.ledger(records, now=5.0)
    spill = folded["epochs"]["2"]["spill"]
    assert spill["resident_bytes"] == 0
    assert spill["freed_bytes"] == 100
    assert folded["live_segments"] == 0


def test_ledger_hardlink_last_link_semantics(cap_env):
    """A slice-published segment (one create carrying all link ids)
    stays resident until its LAST link is deleted — mirroring the
    store's filesystem refcount."""
    records = [
        _rec("create", "seg", 1.0, nbytes=300, tier="shm", epoch=0,
             ids=["l1", "l2", "l3"]),
        _rec("delete", "l2", 2.0),
        _rec("delete", "l1", 3.0),
    ]
    folded = capacity.ledger(records, now=4.0)
    cell = folded["epochs"]["0"]["shm"]
    assert cell["resident_bytes"] == 300 and cell["segments"] == 1
    records.append(_rec("delete", "l3", 5.0))
    folded = capacity.ledger(records, now=6.0)
    cell = folded["epochs"]["0"]["shm"]
    assert cell["resident_bytes"] == 0 and cell["freed_bytes"] == 300


def test_ledger_hwm_and_cleanup(cap_env):
    records = [
        _rec("create", "a", 1.0, nbytes=100, tier="shm", epoch=0),
        _rec("create", "b", 2.0, nbytes=150, tier="shm", epoch=0),
        _rec("delete", "a", 3.0),
        _rec("create", "c", 4.0, nbytes=50, tier="shm", epoch=0),
        _rec("cleanup", "sess", 5.0),
    ]
    folded = capacity.ledger(records, now=6.0)
    cell = folded["epochs"]["0"]["shm"]
    assert cell["hwm_bytes"] == 250  # a+b, before a was freed
    assert cell["resident_bytes"] == 0  # cleanup dropped everything
    assert folded["live_segments"] == 0


def test_store_hooks_attribute_epoch_and_tier(cap_env, tmp_path):
    """The real store paths: put under an ambient epoch context lands
    in the fold under that epoch; free reverses it; slice publish
    keeps the segment until the last window's ref is freed."""
    os.environ["RSDL_SHM_DIR"] = str(tmp_path / "shm")
    store = store_mod.ObjectStore("capsess")
    with trace.context(epoch=7):
        ref = store.put_columns({"a": np.arange(16, dtype=np.int32)})
    folded = capacity.ledger()
    cell = folded["epochs"]["7"]["shm"]
    assert cell["segments"] == 1 and cell["resident_bytes"] > 0
    store.free(ref)
    folded = capacity.ledger()
    assert folded["epochs"]["7"]["shm"]["resident_bytes"] == 0

    with trace.context(epoch=8):
        pending = store.create_columns({"a": ((8,), np.int32)})
        refs = pending.publish_slices([(0, 4), (4, 8)])
    store.free(refs[0])
    assert capacity.ledger()["epochs"]["8"]["shm"]["segments"] == 1
    store.free(refs[1])
    assert capacity.ledger()["epochs"]["8"]["shm"]["resident_bytes"] == 0


def test_store_demote_promote_drop_real_lifecycle(cap_env, tmp_path):
    """ISSUE 10 satellite: the ``transition`` op now has a real
    producer — fold an actual shm→spill→(promote)→spill→drop lifecycle
    driven through ``ObjectStore.demote``/``promote``/``drop_segments``
    (not synthetic records), asserting per-tier residency and high
    watermarks stay exact at every step, including a hardlink-sliced
    segment whose links all move together."""
    os.environ["RSDL_SHM_DIR"] = str(tmp_path / "shm")
    os.environ["RSDL_SPILL_DIR"] = str(tmp_path / "spill")
    store = store_mod.ObjectStore("tiersess")
    with trace.context(epoch=3):
        ref = store.put_columns({"a": np.arange(256, dtype=np.int32)})
        pending = store.create_columns({"b": ((64,), np.int32)})
        sliced = pending.publish_slices([(0, 32), (32, 64)])
    nbytes = ref.nbytes
    sliced_bytes = sliced[0].nbytes
    folded = capacity.ledger()
    cell = folded["epochs"]["3"]["shm"]
    assert cell["resident_bytes"] == nbytes + sliced_bytes
    shm_hwm = cell["hwm_bytes"]
    assert shm_hwm == nbytes + sliced_bytes

    # Demote the plain segment: bytes MOVE (shm frees nothing), the
    # file is physically on the spill tier, and reads keep working.
    assert store.demote(ref.object_id) == nbytes
    folded = capacity.ledger()
    cell = folded["epochs"]["3"]["shm"]
    assert cell["resident_bytes"] == sliced_bytes
    assert cell["freed_bytes"] == 0  # moved, not freed
    assert cell["hwm_bytes"] == shm_hwm  # watermark remembers the peak
    spill = folded["epochs"]["3"]["spill"]
    assert spill["resident_bytes"] == nbytes
    assert spill["hwm_bytes"] == nbytes
    assert store.tier_of(store._find_segment(ref.object_id)) == "spill"
    assert store.get_columns(ref)["a"][11] == 11

    # Promote it back: residency returns to shm, spill zeroes, and the
    # spill watermark remembers ITS peak.
    assert store.promote(ref.object_id) == nbytes
    folded = capacity.ledger()
    assert folded["epochs"]["3"]["shm"]["resident_bytes"] == (
        nbytes + sliced_bytes
    )
    assert folded["epochs"]["3"]["spill"]["resident_bytes"] == 0
    assert folded["epochs"]["3"]["spill"]["hwm_bytes"] == nbytes

    # Demote the hardlink-sliced segment: every window ref must keep
    # resolving (all links moved together), and the fold still counts
    # the inode once.
    link_ids = [r.object_id for r in sliced]
    assert store.demote(link_ids) == sliced_bytes
    for r in sliced:
        assert store.get_columns(r).num_rows == 32
    folded = capacity.ledger()
    assert folded["epochs"]["3"]["spill"]["resident_bytes"] == (
        sliced_bytes
    )
    assert folded["epochs"]["3"]["spill"]["segments"] == 1

    # Drop rungs: demote the plain one again, then drop both. The
    # residency reconciles to zero per tier; re-reads raise
    # ObjectLostError (the lineage-recovery trigger).
    assert store.demote(ref.object_id) == nbytes
    assert store.drop_segments(ref.object_id) == nbytes
    assert store.drop_segments(link_ids) == sliced_bytes
    folded = capacity.ledger()
    assert folded["epochs"]["3"]["shm"]["resident_bytes"] == 0
    assert folded["epochs"]["3"]["spill"]["resident_bytes"] == 0
    assert folded["live_segments"] == 0
    with pytest.raises(store_mod.ObjectLostError):
        store.get_columns(ref)
    # The evictor's candidate feed agrees: nothing live remains.
    assert capacity.live_segments() == []
    store.cleanup()


def test_live_segments_feed(cap_env):
    """``capacity.live_segments`` (the evictor's candidate list): link
    ids, tier, epoch key, oldest-first order, transition-aware."""
    records = [
        _rec("create", "b", 2.0, nbytes=200, tier="shm", epoch=1),
        _rec("create", "a", 1.0, nbytes=100, tier="shm", epoch=0,
             ids=["a1", "a2"]),
        _rec("transition", "a1", 3.0, tier="spill"),
        _rec("create", "c", 4.0, nbytes=50, tier="shm"),  # unknown epoch
    ]
    segs = capacity.live_segments(records)
    assert [s["id"] for s in segs] == ["a", "b", "c"]
    assert segs[0]["ids"] == ["a1", "a2"]
    assert segs[0]["tier"] == "spill"  # the transition moved it
    assert segs[0]["epoch"] == "0"
    assert segs[1]["tier"] == "shm" and segs[1]["epoch"] == "1"
    assert segs[2]["epoch"] == "-"
    records.append(_rec("delete", "a1", 5.0))
    records.append(_rec("delete", "a2", 6.0))
    segs = capacity.live_segments(records)
    assert [s["id"] for s in segs] == ["b", "c"]


def test_touch_tracks_last_access(cap_env, tmp_path):
    """ISSUE 11 satellite: the ``touch`` op (store read paths) stamps a
    segment's last access — the fold carries ``last_touch`` (creation
    counts as the first access; window-link touches resolve to their
    segment; unknown ids are ignored), and the real store emits it from
    ``get_columns`` for plain and hardlink-sliced refs alike."""
    # Synthetic fold semantics.
    records = [
        _rec("create", "a", 1.0, nbytes=100, tier="shm", epoch=0,
             ids=["a1", "a2"]),
        _rec("create", "b", 2.0, nbytes=200, tier="shm", epoch=1),
        _rec("touch", "a2", 5.0),  # link touch -> segment "a"
        _rec("touch", "ghost", 9.0),  # unknown id: ignored
        _rec("touch", "b", 3.5),
    ]
    segs = {s["id"]: s for s in capacity.live_segments(records)}
    assert segs["a"]["last_touch"] == 5.0
    assert segs["b"]["last_touch"] == 3.5
    # An out-of-order (older) touch never rewinds the stamp.
    records.append(_rec("touch", "b", 3.0))
    segs = {s["id"]: s for s in capacity.live_segments(records)}
    assert segs["b"]["last_touch"] == 3.5

    # The real store: reads refresh last_touch through get_columns.
    os.environ["RSDL_SHM_DIR"] = str(tmp_path / "shm")
    store = store_mod.ObjectStore("touchsess")
    with trace.context(epoch=2):
        ref = store.put_columns({"a": np.arange(64, dtype=np.int32)})
        pending = store.create_columns({"b": ((32,), np.int32)})
        sliced = pending.publish_slices([(0, 16), (16, 32)])
    seg0 = {s["id"]: s for s in capacity.live_segments()}
    time.sleep(0.02)
    assert store.get_columns(ref)["a"][5] == 5
    assert store.get_columns(sliced[1]).num_rows == 16
    seg1 = {s["id"]: s for s in capacity.live_segments()}
    for sid in seg0:
        assert seg1[sid]["last_touch"] > seg0[sid]["last_touch"]
    store.cleanup()


def test_cache_tier_fold_and_used_frac(cap_env):
    """The logical ``cache`` tier (shared decode-cache segments): totals
    fold separately, but the shm used fraction counts them — the bytes
    physically live on shm and pressure must see them."""
    records = [
        _rec("create", "e", 1.0, nbytes=600, tier="shm", epoch=0),
        _rec("create", "c", 2.0, nbytes=400, tier="cache", epoch=0),
    ]
    folded = capacity.ledger(records)
    assert folded["totals"]["cache"]["resident_bytes"] == 400
    assert folded["totals"]["shm"]["resident_bytes"] == 600
    assert folded["epochs"]["0"]["cache"]["hwm_bytes"] == 400
    view = capacity.view(records=records)
    host = view.get("host", {})
    if host.get("capacity_bytes"):
        expect = 1000 / host["capacity_bytes"]
        assert view["shm_used_frac"] == pytest.approx(expect, abs=1e-4)


def test_spill_volume_exact_under_rate_limit(cap_env, monkeypatch):
    """The spill satellite: the 1/5s event rate limit must not drop
    byte totals — every call lands on store.spill_bytes_total, and the
    next emitted event carries the accumulated nbytes of everything
    suppressed since the last one."""
    monkeypatch.setattr(store_mod, "_spill_event_last", 0.0)
    monkeypatch.setattr(store_mod, "_spill_pending_bytes", 0)
    monkeypatch.setattr(store_mod, "_spill_pending_events", 0)
    store_mod._emit_spill_event(100)  # emits (interval elapsed)
    store_mod._emit_spill_event(200)  # suppressed
    store_mod._emit_spill_event(300)  # suppressed
    # Force the interval open and emit again: the event must carry the
    # running sum of the suppressed bytes plus its own.
    monkeypatch.setattr(store_mod, "_spill_event_last", 0.0)
    store_mod._emit_spill_event(400)
    snap = metrics.registry.snapshot()
    assert snap["store.spill_bytes_total"] == 1000.0
    spills = [r for r in events.load() if r["kind"] == "store.spill"]
    assert len(spills) == 2
    assert spills[0]["nbytes"] == 100
    assert spills[1]["nbytes"] == 900  # 200 + 300 + 400
    assert spills[1]["events_folded"] == 3
    assert sum(r["nbytes"] for r in spills) == 1000


def test_publish_metrics_gauges_and_zeroing(cap_env):
    records = [
        _rec("create", "a", 1.0, nbytes=100, tier="shm", epoch=0),
    ]
    capacity.publish_metrics(capacity.view(records=records))
    snap = metrics.registry.snapshot()
    assert snap["capacity.resident_bytes{epoch=0,tier=shm}"] == 100.0
    assert snap["capacity.tier_resident_bytes{tier=shm}"] == 100.0
    assert snap.get("capacity.host_rss_bytes", 0) > 0
    # The epoch's segments all freed: its pair leaves the view and the
    # stale gauge must be zeroed, not left at 100.
    records.append(_rec("delete", "a", 2.0))
    capacity.publish_metrics(capacity.view(records=records))
    snap = metrics.registry.snapshot()
    assert snap["capacity.resident_bytes{epoch=0,tier=shm}"] == 0.0


def test_spool_roundtrip_and_dir_load(cap_env):
    capacity.note("create", "x", nbytes=64, tier="shm", epoch=1)
    capacity.flush()
    spool = capacity.spool_dir()
    assert spool and os.path.isdir(spool)
    files = [f for f in os.listdir(spool) if f.startswith("ledger-")]
    assert len(files) == 1
    # Post-hoc load (explicit path, as epoch_report does).
    records = capacity.load_records(path=spool)
    assert len(records) == 1 and records[0]["op"] == "create"
    # The live load (buffer drained by the flush) sees the same.
    folded = capacity.ledger()
    assert folded["epochs"]["1"]["shm"]["resident_bytes"] == 64


def test_epoch_report_capacity_table(cap_env, tmp_path, capsys):
    """tools/epoch_report.py --capacity renders the residency table
    (exit 0 with data, 3 when present-but-empty, 0 with a note when
    absent — the zero-coverage rule)."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "epoch_report_cap", os.path.join(repo, "tools", "epoch_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    ledger_file = tmp_path / "ledger-1.ndjson"
    with open(ledger_file, "w") as f:
        for rec in (
            _rec("create", "a", 1.0, nbytes=1_000_000, tier="shm",
                 epoch=0),
            _rec("create", "b", 2.0, nbytes=2_000_000, tier="spill",
                 epoch=1),
            _rec("delete", "a", 3.0),
        ):
            f.write(json.dumps(rec) + "\n")
    rc = mod.main(["--capacity", str(ledger_file)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "capacity ledger" in out
    assert "spill" in out and "shm" in out

    empty = tmp_path / "ledger-2.ndjson"
    empty.write_text("")
    assert mod.main(["--capacity", str(empty)]) == 3
    rc = mod.main(["--bench", _bench_json(tmp_path),
                   "--capacity", str(tmp_path / "nope")])
    assert rc == 0  # absent artifact: informational note only


def _bench_json(tmp_path) -> str:
    path = tmp_path / "bench.json"
    path.write_text(json.dumps({"value": 1.0, "stall_pct": 0.0}))
    return str(path)


# ---------------------------------------------------------------------------
# Zero-overhead proof for the decision plane (ISSUE 9 acceptance)
# ---------------------------------------------------------------------------

_ZERO_OVERHEAD_SCRIPT = r"""
import os, sys
for k in ("RSDL_METRICS", "RSDL_OBS_PORT", "RSDL_TS", "RSDL_METRICS_DIR",
          "RSDL_EVENTS_DIR", "RSDL_TRACE", "RSDL_AUDIT",
          "RSDL_SLO_RULES"):
    os.environ.pop(k, None)
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from ray_shuffling_data_loader_tpu import runtime
ctx = runtime.init(num_workers=1)
# Exercise the instrumented store paths: create, slice-publish, fetch
# (local), free — with the plane off these must do no ledger work.
store = ctx.store
ref = store.put_columns({"a": np.arange(8, dtype=np.int32)})
store.get_columns(ref)
store.free(ref)
pending = store.create_columns({"a": ((8,), np.int32)})
refs = pending.publish_slices([(0, 4), (4, 8)])
store.free(refs)
# No decision-plane module was ever imported ...
for mod in ("capacity", "critical", "slo", "obs_server", "timeseries"):
    name = "ray_shuffling_data_loader_tpu.telemetry." + mod
    assert name not in sys.modules, name
# ... and no ledger spool exists in the session dir.
assert not os.path.isdir(
    os.path.join(ctx.runtime_dir, "metrics", "capacity")
)
runtime.shutdown()
print("DECISION-ZERO-OVERHEAD-OK")
"""


def test_zero_overhead_when_disabled():
    """ISSUE 9 acceptance: with RSDL_METRICS/RSDL_OBS_PORT unset the
    capacity/critical/slo modules are never imported, no ledger file
    exists, and the store paths run un-instrumented — proven in a
    fresh interpreter."""
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("RSDL_")
    }
    proc = subprocess.run(
        [sys.executable, "-c", _ZERO_OVERHEAD_SCRIPT],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr
    assert "DECISION-ZERO-OVERHEAD-OK" in proc.stdout
