"""Pod-scale resident shuffle: two real ``jax.distributed`` CPU processes
(4 virtual devices each → one 8-device global mesh) stage their
addressable row ranges, assemble the global resident buffer, and run
globally-SPMD epoch shuffles — per-batch gathers cross the pod as XLA
collectives. Asserts exactly-once delivery across the two processes'
addressable shards and cross-process determinism.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["RSDL_T_REPO"])

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["RSDL_T_COORD"],
    num_processes=2,
    process_id=int(os.environ["RSDL_T_RANK"]),
)
assert jax.process_count() == 2
assert len(jax.devices()) == 8

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import Mesh

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_data
from ray_shuffling_data_loader_tpu.resident import (
    DeviceResidentShufflingDataset,
)

rank = int(os.environ["RSDL_T_RANK"])
rdv = os.environ["RSDL_T_RDV"]
# Overridable so tools/measure_pod_gather.py can reuse this harness at
# measurement scale.
NUM_ROWS = int(os.environ.get("RSDL_T_ROWS", "8000"))
BATCH = int(os.environ.get("RSDL_T_BATCH", "1000"))

# Each process runs its own runtime session: staging is process-local by
# design (each host decodes the files overlapping its row range).
runtime.init(num_workers=2)
if rank == 0:
    # num_files=3 floors to 2666 rows/file and actually writes FOUR
    # files (2666 x 3 + a 2-row tail); what matters here: the process
    # boundary (row 4000) straddles file 1, so the row-group-granular
    # range decode path is genuinely exercised.
    generate_data(NUM_ROWS, 3, 2, 0.0, rdv + "/data_tmp")
    os.rename(rdv + "/data_tmp", rdv + "/data")
else:
    deadline = time.time() + 120
    while not os.path.isdir(rdv + "/data"):
        assert time.time() < deadline
        time.sleep(0.2)
filenames = sorted(
    os.path.join(rdv, "data", f)
    for f in os.listdir(rdv + "/data")
    if ".parquet" in f
)

# 2-axis mesh on purpose: model-replicated devices report duplicate row
# spans, which pod staging must deduplicate (dp x tp pods).
mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))


def shard_keys(arr):
    # Model-replicated shards hold identical data; count each row span once.
    seen, keys = set(), []
    for shard in arr.addressable_shards:
        idx = tuple((s.start, s.stop) for s in shard.index)
        if idx not in seen:
            seen.add(idx)
            keys.extend(np.asarray(shard.data).reshape(-1).tolist())
    return keys
from ray_shuffling_data_loader_tpu.resident import fits_device

# Pod auto-select: single-process callers keep the safe False; the SPMD
# pod-consistent vote reaches consensus (True here: CPU backend with
# RSDL_RESIDENT_BUDGET_GB opt-in set below).
assert fits_device(filenames, 2, mesh=mesh) is False
os.environ["RSDL_RESIDENT_BUDGET_GB"] = "4"
assert (
    fits_device(filenames, 2, mesh=mesh, pod_consistent=True) is True
)
del os.environ["RSDL_RESIDENT_BUDGET_GB"]

ds = DeviceResidentShufflingDataset(
    filenames,
    num_epochs=2,
    batch_size=BATCH,
    feature_columns=["key", "embeddings_name0"],
    label_column="labels",
    mesh=mesh,
    seed=11,
)
assert ds.num_rows == NUM_ROWS

assert ds._materialize is True  # tiny dataset: auto picks one-gather

# Second instance pins the per-batch gather path — the schedule large
# pod datasets take when the epoch copy does not fit — which must
# produce the IDENTICAL stream under multi-controller SPMD.
ds_gather = DeviceResidentShufflingDataset(
    filenames,
    num_epochs=2,
    batch_size=BATCH,
    feature_columns=["key", "embeddings_name0"],
    label_column="labels",
    mesh=mesh,
    seed=11,
    materialize_epoch=False,
)

mean_fn = jax.jit(lambda label: jnp.mean(label))
out = {"epochs": [], "gather_epochs": []}
for epoch in range(2):
    ds.set_epoch(epoch)
    t0 = time.perf_counter()
    local_keys = []
    for features, label in ds:
        key_arr = features["key"]
        assert key_arr.shape[0] == BATCH  # global batch
        m = float(mean_fn(label))  # collective across the pod
        assert np.isfinite(m)
        local_keys.extend(shard_keys(key_arr))
    out.setdefault("mat_epoch_s", []).append(time.perf_counter() - t0)
    out["epochs"].append(local_keys)

ds_gather.set_epoch(0)
t0 = time.perf_counter()
gather_keys = []
for features, label in ds_gather:
    jax.block_until_ready(label)
    gather_keys.extend(shard_keys(features["key"]))
out["gather_epoch_s"] = time.perf_counter() - t0
out["gather_epochs"].append(gather_keys)

# Staging-stat sanity (VERDICT r3 item 5): the pod resident loader must
# report its staging through the same instrumentation the bench reads.
out["stats"] = ds.stats.as_dict()
out["gather_stats"] = ds_gather.stats.as_dict()

with open(f"{rdv}/keys_{rank}.tmp", "w") as f:
    json.dump(out, f)
os.rename(f"{rdv}/keys_{rank}.tmp", f"{rdv}/keys_{rank}")
multihost_utils.sync_global_devices("done")
runtime.shutdown()
print("RESPOD_RANK_DONE", rank, flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_resident_shuffle(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    procs, logs = [], []
    for rank in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            RSDL_T_REPO=_REPO,
            RSDL_T_COORD=coord,
            RSDL_T_RANK=str(rank),
            RSDL_T_RDV=str(tmp_path),
            # Pin the workload: the worker reads these (measurement-tool
            # knobs) from the env, and the assertions below are exact.
            RSDL_T_ROWS="8000",
            RSDL_T_BATCH="1000",
        )
        log = tmp_path / f"rank{rank}.log"
        logs.append(log)
        lf = open(log, "w")
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, "-u", "-c", _WORKER],
                    stdout=lf,
                    stderr=subprocess.STDOUT,
                    env=env,
                ),
                lf,
            )
        )
    try:
        for proc, _ in procs:
            proc.wait(timeout=420)
    finally:
        for proc, lf in procs:
            proc.kill()
            proc.wait()
            lf.close()
    outputs = [log.read_text() for log in logs]
    for rank, out in enumerate(outputs):
        assert f"RESPOD_RANK_DONE {rank}" in out, (
            f"rank{rank} log:\n{out[-4000:]}\n--- other rank:\n"
            f"{outputs[1 - rank][-4000:]}"
        )
    results = [
        json.load(open(tmp_path / f"keys_{rank}")) for rank in range(2)
    ]
    for epoch in range(2):
        k0 = results[0]["epochs"][epoch]
        k1 = results[1]["epochs"][epoch]
        # Disjoint addressable shards, together exactly the full dataset.
        assert len(set(k0)) == len(k0)
        assert len(set(k1)) == len(k1)
        assert not (set(k0) & set(k1))
        assert sorted(k0 + k1) == list(range(8000))
    # Different epochs shuffle differently.
    assert results[0]["epochs"][0] != results[0]["epochs"][1]
    # The per-batch gather schedule yields the identical stream.
    for rank in range(2):
        assert (
            results[rank]["gather_epochs"][0] == results[rank]["epochs"][0]
        )
    # Staging-stat sanity: every process staged its addressable share
    # (2 feature cols + label + key padding aside, > 0 bytes / batches),
    # the one-time staging pass is timed, and the per-batch gather
    # schedule reports its delivery through the same counters.
    expected_batches = 2 * (8000 // 1000)  # 2 epochs x 8 full batches
    for rank in range(2):
        st = results[rank]["stats"]
        assert st["bytes_staged"] > 0, st
        assert st["batches_staged"] == expected_batches, st
        assert st["first_batch_s"] and st["first_batch_s"] > 0, st
        gst = results[rank]["gather_stats"]
        assert gst["bytes_staged"] > 0, gst
        assert gst["batches_staged"] == 8000 // 1000, gst
        assert results[rank]["gather_epoch_s"] > 0
        assert len(results[rank]["mat_epoch_s"]) == 2
