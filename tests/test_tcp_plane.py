"""Two-process loopback TCP-plane integration test (ISSUE 5): the
`bench.py --plane tcp` mode at a tiny shape — a worker host joins over
TCP with its own shm dir, the windowed-fetch microbench runs both
framings, and the end-to-end two-host shuffle reconciles exactly-once
over the new transport path (audit ok=true)."""

import json
import os
import subprocess
import sys

import pytest

slow = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@slow
def test_tcp_plane_loopback_bench(tmp_path):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        RSDL_BENCH_TCP_WINDOWS="12",
        RSDL_BENCH_TCP_WINDOW_MB="1",
        RSDL_BENCH_TCP_SHUFFLE_GB="0.02",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--plane", "tcp"],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["plane"] == "tcp"
    assert "error" not in result, result
    fetch = result["fetch"]
    # All three planes measured, and the wire planes actually moved the
    # published bytes.
    for key in ("shm_gbps", "tcp_pickle_gbps", "tcp_zerocopy_gbps"):
        assert fetch[key] > 0, (key, fetch)
    assert fetch["raw_loopback_gbps"] > 0
    assert fetch["hmac_handshake_ms"] > 0
    for plane in ("shm", "tcp_pickle", "tcp_zerocopy"):
        assert fetch["window_ms"][plane]["mean"] > 0
    # The end-to-end two-host shuffle must have moved bytes across hosts
    # in BOTH directions (scatter with locality disabled) and reconciled
    # exactly-once over the TCP plane.
    sh = result["shuffle"]
    assert sh["audit_ok"] is True
    served = sh["served_cross_host"]
    assert served["head"]["bytes"] > 0
    assert served["worker"]["bytes"] > 0
    assert sh["delivered_gb"] > 0
