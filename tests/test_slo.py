"""SLO alert-engine tests (ISSUE 9): rule evaluation (threshold fire +
resolve, for_s pending, absence, rate-over-window via the timeseries
ring), rule-source merging (RSDL_SLO_RULES overrides/disables the
default pack), the alert.fired/alert.resolved event + gauge surface —
and the chaos integration: a ``wedge`` fault injected into a reduce
task must fire (and later resolve) the default ``wedged_worker`` alert
with exactly-once delivery intact (function-scoped runtimes, per the
obs/chaos test convention)."""

import json
import os
import threading
import time

import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.runtime import faults
from ray_shuffling_data_loader_tpu.telemetry import (
    events,
    metrics,
    slo,
    stragglers,
    timeseries,
)

_ENV = (
    "RSDL_METRICS", "RSDL_METRICS_DIR", "RSDL_OBS_PORT", "RSDL_TS",
    "RSDL_SLO_RULES", "RSDL_EVENTS_DIR",
    "RSDL_FAULTS", "RSDL_FAULTS_SEED", "RSDL_FAULTS_WEDGE_S",
    "RSDL_STRAGGLER_K", "RSDL_STRAGGLER_MIN_S",
    "RSDL_AUDIT", "RSDL_AUDIT_DIR",
)


@pytest.fixture
def slo_env(tmp_path):
    saved = {k: os.environ.get(k) for k in _ENV}
    os.environ["RSDL_METRICS"] = "1"
    os.environ["RSDL_METRICS_DIR"] = str(tmp_path / "metrics-spool")
    for k in _ENV[2:]:
        os.environ.pop(k, None)
    metrics.refresh_from_env()
    metrics.reset()
    timeseries.reset()
    events.reset()
    slo.reset()
    yield
    slo.reset()
    timeseries.stop()
    timeseries.reset()
    events.reset()
    stragglers.reset(clear_spool=True)
    metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    metrics.refresh_from_env()
    faults.refresh_from_env()


def _rule(**kv):
    os.environ["RSDL_SLO_RULES"] = json.dumps([kv])
    slo.reset()


def _alert_events(kind):
    return [r for r in events.load() if r.get("kind") == kind]


def test_threshold_fire_and_resolve(slo_env):
    _rule(name="trip", kind="threshold", metric="x.level", op=">",
          value=10)
    metrics.registry.gauge("x.level").set(5)
    out = slo.evaluate(now=100.0)
    assert out["active"] == []
    metrics.registry.gauge("x.level").set(25)
    out = slo.evaluate(now=101.0)
    assert out["active"] == ["trip"]
    row = next(r for r in out["rules"] if r["name"] == "trip")
    assert row["value"] == 25.0 and row["fired_count"] == 1
    assert metrics.registry.snapshot()["alert.active{rule=trip}"] == 1.0
    fired = _alert_events("alert.fired")
    assert fired and fired[-1]["rule"] == "trip"
    assert fired[-1]["value"] == 25.0
    # Still over: stays firing, no duplicate fire event.
    out = slo.evaluate(now=102.0)
    assert out["active"] == ["trip"]
    assert len(_alert_events("alert.fired")) == 1
    # Clears: resolves, gauge drops, resolve event lands.
    metrics.registry.gauge("x.level").set(0)
    out = slo.evaluate(now=103.0)
    assert out["active"] == []
    assert metrics.registry.snapshot()["alert.active{rule=trip}"] == 0.0
    resolved = _alert_events("alert.resolved")
    assert resolved and resolved[-1]["rule"] == "trip"
    assert slo.fired_counts() == {"trip": 1}


def test_for_s_holds_before_firing(slo_env):
    _rule(name="slowtrip", kind="threshold", metric="x.level", op=">",
          value=0, for_s=5.0)
    metrics.registry.gauge("x.level").set(1)
    assert slo.evaluate(now=100.0)["active"] == []  # pending
    assert slo.evaluate(now=103.0)["active"] == []  # still pending
    assert slo.evaluate(now=105.5)["active"] == ["slowtrip"]
    # A dip back under before for_s elapses resets the clock.
    _rule(name="slowtrip", kind="threshold", metric="x.level", op=">",
          value=0, for_s=5.0)
    metrics.registry.gauge("x.level").set(1)
    slo.evaluate(now=200.0)
    metrics.registry.gauge("x.level").set(0)
    slo.evaluate(now=202.0)  # pending -> ok, no fire
    metrics.registry.gauge("x.level").set(1)
    slo.evaluate(now=203.0)
    assert slo.evaluate(now=206.0)["active"] == []  # only 3 s held
    assert slo.evaluate(now=208.5)["active"] == ["slowtrip"]


def test_absence_rule(slo_env):
    _rule(name="missing", kind="absence", metric="heartbeat.count")
    out = slo.evaluate(now=100.0)
    assert out["active"] == ["missing"]  # metric absent entirely
    metrics.registry.counter("heartbeat.count").inc()
    out = slo.evaluate(now=101.0)
    assert out["active"] == []  # present: resolved
    assert slo.fired_counts() == {"missing": 1}
    assert _alert_events("alert.resolved")[-1]["rule"] == "missing"


def test_rate_rule_over_ring_window(slo_env):
    """A rate rule reads the sampler ring: a counter advancing slower
    than the floor fires; speeding it back up resolves."""
    _rule(name="slow_rows", kind="rate", metric="y.rows", op="<",
          value=5.0, window_s=60.0)
    counter = metrics.registry.counter("y.rows")
    # No samples yet: unknown, must NOT fire on ignorance.
    assert slo.evaluate(now=999.0)["active"] == []
    counter.inc(100)
    timeseries.sample_now(now=1000.0)
    counter.inc(2)  # 2 rows / 2 s = 1 row/s < 5
    timeseries.sample_now(now=1002.0)
    out = slo.evaluate(now=1002.5)
    assert out["active"] == ["slow_rows"]
    counter.inc(200)  # 100 rows/s over the next step
    timeseries.sample_now(now=1004.0)
    # The 60 s window still averages in the slow sample; shrink via a
    # fresh fast-only window.
    _rule(name="slow_rows", kind="rate", metric="y.rows", op="<",
          value=5.0, window_s=1.0)
    out = slo.evaluate(now=1004.5)
    assert out["active"] == []


def test_rate_fold_max_source_normalizes_by_consumer(slo_env):
    """A share-of-wall-clock rule with fold=max-source keys on the
    WORST source, not the cluster sum: two consumers each 30 % stalled
    must not trip a 50 % budget (the sum, 60 %, would)."""
    import socket as _socket

    spool = os.environ["RSDL_METRICS_DIR"]

    def _write(pid, value, ts):
        os.makedirs(spool, exist_ok=True)
        path = os.path.join(spool, f"metrics-task-{pid}.json")
        with open(path, "w") as f:
            json.dump(
                {
                    "source": {"role": "task", "pid": pid,
                               "host": _socket.gethostname()},
                    "ts": ts,
                    "metrics": {
                        "stall_seconds{cause=upstream}": {
                            "kind": "counter", "value": value,
                        }
                    },
                },
                f,
            )

    _write(111, 0.0, time.time())
    _write(222, 0.0, time.time())
    timeseries.sample_now(now=1000.0)
    _write(111, 3.0, time.time())  # 3 s stalled over a 10 s step = 30%
    _write(222, 3.0, time.time())
    timeseries.sample_now(now=1010.0)

    _rule(name="worst", kind="rate", metric="stall_seconds", op=">",
          value=0.5, window_s=60.0, fold="max-source")
    assert slo.evaluate(now=1010.5)["active"] == []
    _rule(name="summed", kind="rate", metric="stall_seconds", op=">",
          value=0.5, window_s=60.0)
    assert slo.evaluate(now=1010.5)["active"] == ["summed"]


def test_user_rules_override_and_disable_defaults(slo_env):
    names = {r["name"] for r in slo.rules()}
    # The default pack ships the ISSUE 9 five.
    for expected in ("producer_stalled", "stall_over_budget",
                     "capacity_near_limit", "wedged_worker",
                     "audit_mismatch"):
        assert expected in names
    os.environ["RSDL_SLO_RULES"] = json.dumps([
        {"name": "wedged_worker", "kind": "threshold",
         "metric": "straggler.wedged_tasks", "op": ">", "value": 3},
        {"name": "audit_mismatch", "disabled": True},
        {"name": "mine", "kind": "threshold", "metric": "z", "op": ">",
         "value": 0},
    ])
    slo.reset()
    by_name = {r["name"]: r for r in slo.rules()}
    assert by_name["wedged_worker"]["value"] == 3  # overridden
    assert "audit_mismatch" not in by_name  # disabled
    assert "mine" in by_name  # added
    assert "producer_stalled" in by_name  # untouched default


def test_base_name_sums_labeled_series(slo_env):
    """A rule on a base name covers every labeled series of it — the
    stall budget rule sums both causes."""
    _rule(name="sum", kind="threshold", metric="stall_seconds", op=">",
          value=10)
    metrics.registry.counter("stall_seconds", cause="upstream").inc(7)
    metrics.registry.counter("stall_seconds", cause="staging").inc(6)
    out = slo.evaluate(now=100.0)
    assert out["active"] == ["sum"]
    row = next(r for r in out["rules"] if r["name"] == "sum")
    assert row["value"] == 13.0


def test_prom_alias_accepted(slo_env):
    _rule(name="alias", kind="threshold", metric="rsdl_x_level",
          op=">", value=0)
    metrics.registry.gauge("x.level").set(1)
    assert slo.evaluate(now=100.0)["active"] == ["alias"]


def test_headroom_low_and_drain_stuck_fire_and_resolve(slo_env, tmp_path):
    """ISSUE 10 satellite: the new elastic default rules. A store
    filled past its budget drives ``elastic.shm_headroom_frac`` under
    the rule floor — ``headroom_low`` FIRES; a forced demotion relieves
    the pressure and it RESOLVES. A drain whose in-flight wait has aged
    past the deadline drives ``elastic.drain_age_seconds`` over the
    rule bound — ``drain_stuck`` fires, and resolves when the drain
    completes (the controller zeroes the gauge)."""
    import types

    import numpy as np

    from ray_shuffling_data_loader_tpu.runtime import (
        elastic as elastic_mod,
    )
    from ray_shuffling_data_loader_tpu.runtime.store import ObjectStore
    from ray_shuffling_data_loader_tpu.telemetry import capacity, trace

    os.environ["RSDL_SHM_DIR"] = str(tmp_path / "shm")
    os.environ["RSDL_SPILL_DIR"] = str(tmp_path / "spill")
    os.environ["RSDL_STORE_CAPACITY_BYTES"] = "16384"
    capacity.reset(clear_spool=True)
    store = ObjectStore("slostore")
    ctx = types.SimpleNamespace(
        store=store, cluster=None, session=store.session,
        scheduler=types.SimpleNamespace(width=1), runtime_dir=None,
    )
    ctl = elastic_mod.ElasticController(ctx)
    try:
        # Nearly fill the 16 KiB budget: headroom < 0.1 -> fires.
        with trace.context(epoch=0):
            ref = store.put_columns(
                {"a": np.zeros(3800, np.int32)}  # ~15.2 KiB segment
            )
        ctl.publish_gauges()
        out = slo.evaluate(now=100.0)
        assert "headroom_low" in out["active"]
        assert metrics.registry.snapshot()[
            "alert.active{rule=headroom_low}"
        ] == 1.0
        # Forced demotion moves the bytes to the spill tier; headroom
        # recovers and the alert resolves.
        stats = ctl.evict_once(force=True)
        assert stats["demoted"] == 1
        ctl.publish_gauges()
        out = slo.evaluate(now=101.0)
        assert "headroom_low" not in out["active"]
        resolved = [r for r in _alert_events("alert.resolved")
                    if r.get("rule") == "headroom_low"]
        assert resolved

        # drain_stuck: an active drain aged past the rule bound (the
        # gauge the drain wait-loop maintains) fires; completion (the
        # controller clears its started-set and republishes) resolves.
        ctl._drain_started[("tcp", "w", 1)] = time.monotonic() - 60.0
        ctl.publish_gauges()
        out = slo.evaluate(now=102.0)
        assert "drain_stuck" in out["active"]
        ctl._drain_started.clear()
        ctl.publish_gauges()
        out = slo.evaluate(now=103.0)
        assert "drain_stuck" not in out["active"]
        resolved = [r for r in _alert_events("alert.resolved")
                    if r.get("rule") == "drain_stuck"]
        assert resolved
    finally:
        store.cleanup()
        capacity.reset(clear_spool=True)
        for k in ("RSDL_SHM_DIR", "RSDL_SPILL_DIR",
                  "RSDL_STORE_CAPACITY_BYTES"):
            os.environ.pop(k, None)


# ---------------------------------------------------------------------------
# Tenant-scoped instances (ISSUE 16): per_job expansion
# ---------------------------------------------------------------------------


def test_per_job_rule_fires_only_the_stalled_tenant(slo_env):
    """A ``per_job`` threshold rule expands over the ``job=`` labels in
    the aggregate: the tenant over the bound fires ``rule|job`` (a
    job-labeled ``alert.active`` gauge plus job-stamped events) while
    its neighbor stays ok; recovering in place resolves that instance
    alone."""
    _rule(name="deep", kind="threshold", metric="q.depth", op=">",
          value=5, per_job=True)
    metrics.registry.gauge("q.depth", job="a").set(10)
    metrics.registry.gauge("q.depth", job="b").set(1)
    out = slo.evaluate(now=100.0)
    assert out["jobs"] == ["a", "b"]
    assert out["active"] == ["deep|a"]
    rows = {r["job"]: r for r in out["rules"] if r["name"] == "deep"}
    assert rows["a"]["active"] and rows["a"]["value"] == 10.0
    assert rows["b"]["state"] == "ok" and rows["b"]["value"] == 1.0
    snap = metrics.registry.snapshot()
    assert snap["alert.active{job=a,rule=deep}"] == 1.0
    assert snap["alert.active{job=b,rule=deep}"] == 0.0
    fired = _alert_events("alert.fired")
    assert fired and fired[-1]["rule"] == "deep"
    assert fired[-1]["job"] == "a"
    assert slo.active_alerts_by_job() == {"a": ["deep"]}
    # a recovers (series still live, back under the bound): in-place
    # resolve, b untouched the whole time.
    metrics.registry.gauge("q.depth", job="a").set(2)
    out = slo.evaluate(now=101.0)
    assert out["active"] == []
    assert metrics.registry.snapshot()[
        "alert.active{job=a,rule=deep}"
    ] == 0.0
    resolved = _alert_events("alert.resolved")
    assert resolved and resolved[-1]["job"] == "a"
    assert not [r for r in events.load() if r.get("job") == "b"]
    assert slo.fired_counts() == {"deep|a": 1}
    assert slo.active_alerts_by_job() == {}


def test_per_job_stale_instance_resolves_on_departure(slo_env):
    """A firing per-job instance whose tenant leaves the live set is
    retired on the next tick: resolve event emitted, gauge zeroed —
    a departed tenant must not hold a page open — and its lifetime
    fire count survives the cleanup."""
    _rule(name="deep", kind="threshold", metric="q.depth", op=">",
          value=5, per_job=True)
    metrics.registry.gauge("q.depth", job="a").set(10)
    metrics.registry.gauge("q.depth", job="b").set(1)
    assert slo.evaluate(now=100.0)["active"] == ["deep|a"]
    # Tenant a departs: its series zeroes out of the label harvest.
    metrics.registry.gauge("q.depth", job="a").set(0)
    out = slo.evaluate(now=101.0)
    assert out["jobs"] == ["b"]
    assert out["active"] == []
    assert metrics.registry.snapshot()[
        "alert.active{job=a,rule=deep}"
    ] == 0.0
    resolved = [r for r in _alert_events("alert.resolved")
                if r.get("job") == "a"]
    assert resolved and resolved[-1]["rule"] == "deep"
    assert slo.fired_counts() == {"deep|a": 1}
    assert slo.active_alerts_by_job() == {}


def test_per_job_metric_points_instances_at_tenant_series(slo_env):
    """``per_job_metric`` swaps the expanded instances onto a different
    (job-labeled) series than the rule's global metric — the
    producer_stalled / capacity_near_limit default shape."""
    _rule(name="mix", kind="threshold", metric="global.x", op=">",
          value=0, per_job=True, per_job_metric="tenant.x")
    metrics.registry.gauge("tenant.x", job="a").set(3)
    metrics.registry.gauge("tenant.x", job="b").set(0)
    metrics.registry.gauge("tenant.busy", job="b").set(1)  # b stays live
    metrics.registry.gauge("global.x").set(99)  # must NOT leak in
    out = slo.evaluate(now=100.0)
    assert out["jobs"] == ["a", "b"]
    assert out["active"] == ["mix|a"]
    rows = {r["job"]: r for r in out["rules"] if r["name"] == "mix"}
    assert rows["a"]["metric"] == "tenant.x"
    assert rows["a"]["value"] == 3.0
    assert rows["b"]["value"] == 0.0


def test_per_job_degrades_to_global_without_tenants(slo_env):
    """With no live jobs a per_job rule is the single global instance
    (service-off runs behave exactly as before); a tenant appearing
    supersedes it — the global instance retires, resolving on the way
    out."""
    _rule(name="deep", kind="threshold", metric="q.depth", op=">",
          value=5, per_job=True)
    metrics.registry.gauge("q.depth").set(10)
    out = slo.evaluate(now=100.0)
    assert out["jobs"] == []
    assert out["active"] == ["deep"]
    assert metrics.registry.snapshot()["alert.active{rule=deep}"] == 1.0
    metrics.registry.gauge("q.depth", job="a").set(1)
    out = slo.evaluate(now=101.0)
    assert out["jobs"] == ["a"]
    assert out["active"] == []
    assert metrics.registry.snapshot()["alert.active{rule=deep}"] == 0.0
    assert [r for r in _alert_events("alert.resolved")
            if r.get("rule") == "deep" and "job" not in r]
    assert slo.fired_counts() == {"deep": 1}


def test_per_job_rate_rule_window_mean_field(slo_env):
    """The admission_wait_long shape: a per-job rate rule with
    ``field=window_mean`` over a job-labeled histogram fires for the
    tenant whose recent observations average over budget only."""
    _rule(name="adm", kind="rate", metric="w.wait", op=">", value=5.0,
          window_s=120.0, per_job=True, field="window_mean")
    metrics.registry.histogram("w.wait", job="a").observe(30.0)
    metrics.registry.histogram("w.wait", job="b").observe(0.1)
    timeseries.sample_now(now=1000.0)
    metrics.registry.histogram("w.wait", job="a").observe(30.0)
    metrics.registry.histogram("w.wait", job="b").observe(0.1)
    timeseries.sample_now(now=1010.0)
    out = slo.evaluate(now=1010.5)
    assert out["active"] == ["adm|a"]
    snap = metrics.registry.snapshot()
    assert snap["alert.active{job=a,rule=adm}"] == 1.0
    assert snap["alert.active{job=b,rule=adm}"] == 0.0


# ---------------------------------------------------------------------------
# Chaos integration: a wedge fault fires (and resolves) the default
# wedged_worker alert (ISSUE 9 acceptance)
# ---------------------------------------------------------------------------

NUM_FILES = 2
ROWS_PER_FILE = 512
NUM_REDUCERS = 4


def test_chaos_wedge_fires_wedged_worker_alert(slo_env, tmp_path):
    """Arm a deterministic ``wedge`` fault on one reduce task: while
    it sleeps, the straggler gauges feed the default ``wedged_worker``
    rule — the alert must FIRE live (event + gauge + /alerts state)
    and RESOLVE after the run drains, with audit ok=true throughout."""
    from ray_shuffling_data_loader_tpu.data_generation import generate_file
    from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle
    from ray_shuffling_data_loader_tpu.telemetry import audit

    os.environ["RSDL_FAULTS"] = "task.reduce/task:wedge:1x1"
    os.environ["RSDL_FAULTS_SEED"] = "42"
    os.environ["RSDL_FAULTS_WEDGE_S"] = "2.5"
    faults.refresh_from_env()
    audit.enable(spool_dir=str(tmp_path / "audit-spool"))
    # One worker process: the x1 cap is per process, so exactly one
    # reduce task wedges and the other three stay fast.
    runtime.init(num_workers=1)
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    files = [
        generate_file(i, i * ROWS_PER_FILE, ROWS_PER_FILE, 1,
                      str(data_dir))[0]
        for i in range(NUM_FILES)
    ]

    class _Consumer(BatchConsumer):
        def __init__(self):
            self.done = threading.Event()

        def consume(self, rank, epoch, batches):
            pass

        def producer_done(self, rank, epoch):
            self.done.set()

        def wait_until_ready(self, epoch):
            pass

        def wait_until_all_epochs_done(self):
            assert self.done.wait(timeout=180)

    errors = []

    def _run():
        try:
            shuffle(
                files, _Consumer(), num_epochs=1,
                num_reducers=NUM_REDUCERS, num_trainers=1, seed=3,
            )
        except BaseException as exc:
            errors.append(exc)

    thread = threading.Thread(target=_run, daemon=True)
    thread.start()
    try:
        # Drive the same refresh the sampler tick runs (stragglers
        # publish, then engine evaluate) until the alert fires.
        fired_state = None
        deadline = time.time() + 120
        while time.time() < deadline:
            stragglers.publish_metrics()
            out = slo.evaluate()
            if "wedged_worker" in out["active"]:
                fired_state = next(
                    r for r in out["rules"]
                    if r["name"] == "wedged_worker"
                )
                break
            time.sleep(0.05)
        assert fired_state is not None, "wedged_worker never fired"
        assert fired_state["value"] >= 1.0
        snap = metrics.registry.snapshot()
        assert snap["alert.active{rule=wedged_worker}"] == 1.0
        fired = [r for r in events.load()
                 if r.get("kind") == "alert.fired"
                 and r.get("rule") == "wedged_worker"]
        assert fired, "no alert.fired event"
        thread.join(timeout=180)
        assert not thread.is_alive()
        assert not errors, errors
        # The wedged task completed: the in-flight set empties, the
        # gauge drops, and the next evaluation resolves the alert.
        deadline = time.time() + 60
        resolved = False
        while time.time() < deadline:
            stragglers.publish_metrics()
            out = slo.evaluate()
            if "wedged_worker" not in out["active"]:
                resolved = True
                break
            time.sleep(0.05)
        assert resolved, "wedged_worker never resolved"
        assert [r for r in events.load()
                if r.get("kind") == "alert.resolved"
                and r.get("rule") == "wedged_worker"]
        # Exactly-once held through the wedge (the chaos bar).
        verdicts = audit.verdicts()
        assert verdicts and all(v["ok"] for v in verdicts)
        assert slo.fired_counts().get("wedged_worker") == 1
    finally:
        thread.join(timeout=5)
        runtime.shutdown()
        audit.disable()
