"""tools/epoch_report.py tests: per-epoch stage breakdown from a trace,
critical-path naming on a stage-dominant fixture, stats-CSV joins, and
the baseline regression gate's exit codes both ways (the CI lane runs
the same checks against the committed fixtures)."""

import importlib.util
import json
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "epoch_report")


@pytest.fixture(scope="module")
def epoch_report():
    path = os.path.join(_REPO, "tools", "epoch_report.py")
    spec = importlib.util.spec_from_file_location("epoch_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _span(name, epoch, start_s, dur_s, **args):
    return {
        "name": name,
        "cat": "x",
        "ph": "X",
        "ts": start_s * 1e6,
        "dur": dur_s * 1e6,
        "pid": 1,
        "tid": 1,
        "args": {"epoch": epoch, **args},
    }


def test_critical_path_names_dominant_stage(epoch_report):
    """A fixture where reduce is artificially dominant must name reduce;
    one where consume dominates must name consume."""
    reduce_heavy = [
        _span("map", 0, 0.0, 1.0),
        _span("reduce", 0, 1.0, 8.0),
        _span("deliver", 0, 9.0, 0.2),
        _span("stage:h2d", 0, 9.1, 0.3),
    ]
    report = epoch_report.build_report(
        reduce_heavy, [], [], None, None, 10.0, 10.0
    )
    (row,) = report["epochs"]
    assert row["critical_path"] == "reduce"
    assert report["header"]["critical_path"] == "reduce"
    assert row["reduce_s"] == pytest.approx(8.0)
    assert row["wall_s"] == pytest.approx(9.4)

    consume_heavy = [
        _span("map", 0, 0.0, 0.5),
        _span("reduce", 0, 0.5, 0.5),
        _span("deliver", 0, 1.0, 0.1),
        _span("stage:h2d", 0, 1.0, 7.0),
    ]
    report = epoch_report.build_report(
        consume_heavy, [], [], None, None, 10.0, 10.0
    )
    assert report["epochs"][0]["critical_path"] == "consume"


def test_overlap_idle_and_union_semantics(epoch_report):
    """Overlapping same-stage tasks count once (interval union); cross-
    stage overlap and idle gaps are decomposed from the epoch window."""
    events = [
        _span("map", 2, 0.0, 2.0),
        _span("map", 2, 1.0, 2.0),      # overlaps the first map task
        _span("reduce", 2, 2.5, 1.0),   # 0.5s overlap with map
        # 1.5s gap (idle), then delivery
        _span("deliver", 2, 5.0, 1.0),
    ]
    report = epoch_report.build_report(events, [], [], None, None, 10, 10)
    (row,) = report["epochs"]
    assert row["map_s"] == pytest.approx(3.0)       # union, not 4.0
    assert row["overlap_s"] == pytest.approx(0.5)
    assert row["idle_s"] == pytest.approx(1.5)
    assert row["wall_s"] == pytest.approx(6.0)


def test_stall_attribution_and_csv_join(epoch_report, tmp_path):
    events = [
        _span("map", 0, 0.0, 1.0),
        _span("stall", 0, 1.0, 0.25, cause="upstream"),
        _span("stall", 0, 1.5, 0.75, cause="staging"),
    ]
    epoch_rows = [
        {
            "trial": "0",
            "epoch": "0",
            "duration": "4.5",
            "throttle_duration": "0.5",
            "map_stage_duration": "1.0",
            "reduce_stage_duration": "2.0",
        }
    ]
    report = epoch_report.build_report(
        events, epoch_rows, [], None, None, 10, 10
    )
    (row,) = report["epochs"]
    assert row["stall_upstream_s"] == pytest.approx(0.25)
    assert row["stall_staging_s"] == pytest.approx(0.75)
    assert row["epoch_s"] == pytest.approx(4.5)
    assert row["throttle_s"] == pytest.approx(0.5)


def test_baseline_gate_exit_codes(epoch_report, capsys):
    """Clean run vs baseline: exit 0; injected regression: exit 1 with a
    REGRESSION line naming the breach — the exact contract the CI lane
    gates on (both directions)."""
    trace = os.path.join(_FIXTURES, "trace.json")
    baseline = os.path.join(_FIXTURES, "baseline.json")
    rc = epoch_report.main(
        [
            "--trace", trace,
            "--epoch-csv", os.path.join(_FIXTURES, "epoch_stats.csv"),
            "--bench", os.path.join(_FIXTURES, "bench_clean.json"),
            "--baseline", baseline,
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "critical_path: reduce" in out

    rc = epoch_report.main(
        [
            "--trace", trace,
            "--bench", os.path.join(_FIXTURES, "bench_regressed.json"),
            "--baseline", baseline,
        ]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out


def test_baseline_accepts_round_capture_wrapper(epoch_report, tmp_path):
    """BENCH_rXX.json wraps the bench line under "parsed" — the gate must
    read both shapes."""
    wrapped = tmp_path / "baseline_wrapped.json"
    wrapped.write_text(
        json.dumps({"n": 5, "parsed": {"value": 1.0, "stall_pct": 10.0}})
    )
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps({"value": 0.2, "stall_pct": 12.0}))
    rc = epoch_report.main(
        ["--bench", str(bench), "--baseline", str(wrapped)]
    )
    assert rc == 1  # 80% throughput drop vs the wrapped baseline


def test_empty_inputs_exit_3(epoch_report, tmp_path):
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"traceEvents": []}))
    rc = epoch_report.main(["--trace", str(empty)])
    assert rc == 3
