"""tools/epoch_report.py tests: per-epoch stage breakdown from a trace,
critical-path naming on a stage-dominant fixture, stats-CSV joins, and
the baseline regression gate's exit codes both ways (the CI lane runs
the same checks against the committed fixtures)."""

import importlib.util
import json
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "epoch_report")


@pytest.fixture(scope="module")
def epoch_report():
    path = os.path.join(_REPO, "tools", "epoch_report.py")
    spec = importlib.util.spec_from_file_location("epoch_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _span(name, epoch, start_s, dur_s, **args):
    return {
        "name": name,
        "cat": "x",
        "ph": "X",
        "ts": start_s * 1e6,
        "dur": dur_s * 1e6,
        "pid": 1,
        "tid": 1,
        "args": {"epoch": epoch, **args},
    }


def test_critical_path_names_dominant_stage(epoch_report):
    """A fixture where reduce is artificially dominant must name reduce;
    one where consume dominates must name consume."""
    reduce_heavy = [
        _span("map", 0, 0.0, 1.0),
        _span("reduce", 0, 1.0, 8.0),
        _span("deliver", 0, 9.0, 0.2),
        _span("stage:h2d", 0, 9.1, 0.3),
    ]
    report = epoch_report.build_report(
        reduce_heavy, [], [], None, None, 10.0, 10.0
    )
    (row,) = report["epochs"]
    assert row["critical_path"] == "reduce"
    assert report["header"]["critical_path"] == "reduce"
    assert row["reduce_s"] == pytest.approx(8.0)
    assert row["wall_s"] == pytest.approx(9.4)

    consume_heavy = [
        _span("map", 0, 0.0, 0.5),
        _span("reduce", 0, 0.5, 0.5),
        _span("deliver", 0, 1.0, 0.1),
        _span("stage:h2d", 0, 1.0, 7.0),
    ]
    report = epoch_report.build_report(
        consume_heavy, [], [], None, None, 10.0, 10.0
    )
    assert report["epochs"][0]["critical_path"] == "consume"


def test_overlap_idle_and_union_semantics(epoch_report):
    """Overlapping same-stage tasks count once (interval union); cross-
    stage overlap and idle gaps are decomposed from the epoch window."""
    events = [
        _span("map", 2, 0.0, 2.0),
        _span("map", 2, 1.0, 2.0),      # overlaps the first map task
        _span("reduce", 2, 2.5, 1.0),   # 0.5s overlap with map
        # 1.5s gap (idle), then delivery
        _span("deliver", 2, 5.0, 1.0),
    ]
    report = epoch_report.build_report(events, [], [], None, None, 10, 10)
    (row,) = report["epochs"]
    assert row["map_s"] == pytest.approx(3.0)       # union, not 4.0
    assert row["overlap_s"] == pytest.approx(0.5)
    assert row["idle_s"] == pytest.approx(1.5)
    assert row["wall_s"] == pytest.approx(6.0)


def test_stall_attribution_and_csv_join(epoch_report, tmp_path):
    events = [
        _span("map", 0, 0.0, 1.0),
        _span("stall", 0, 1.0, 0.25, cause="upstream"),
        _span("stall", 0, 1.5, 0.75, cause="staging"),
    ]
    epoch_rows = [
        {
            "trial": "0",
            "epoch": "0",
            "duration": "4.5",
            "throttle_duration": "0.5",
            "map_stage_duration": "1.0",
            "reduce_stage_duration": "2.0",
        }
    ]
    report = epoch_report.build_report(
        events, epoch_rows, [], None, None, 10, 10
    )
    (row,) = report["epochs"]
    assert row["stall_upstream_s"] == pytest.approx(0.25)
    assert row["stall_staging_s"] == pytest.approx(0.75)
    assert row["epoch_s"] == pytest.approx(4.5)
    assert row["throttle_s"] == pytest.approx(0.5)


def test_baseline_gate_exit_codes(epoch_report, capsys):
    """Clean run vs baseline: exit 0; injected regression: exit 1 with a
    REGRESSION line naming the breach — the exact contract the CI lane
    gates on (both directions)."""
    trace = os.path.join(_FIXTURES, "trace.json")
    baseline = os.path.join(_FIXTURES, "baseline.json")
    rc = epoch_report.main(
        [
            "--trace", trace,
            "--epoch-csv", os.path.join(_FIXTURES, "epoch_stats.csv"),
            "--bench", os.path.join(_FIXTURES, "bench_clean.json"),
            "--baseline", baseline,
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "critical_path: reduce" in out

    rc = epoch_report.main(
        [
            "--trace", trace,
            "--bench", os.path.join(_FIXTURES, "bench_regressed.json"),
            "--baseline", baseline,
        ]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out


def test_baseline_accepts_round_capture_wrapper(epoch_report, tmp_path):
    """BENCH_rXX.json wraps the bench line under "parsed" — the gate must
    read both shapes."""
    wrapped = tmp_path / "baseline_wrapped.json"
    wrapped.write_text(
        json.dumps({"n": 5, "parsed": {"value": 1.0, "stall_pct": 10.0}})
    )
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps({"value": 0.2, "stall_pct": 12.0}))
    rc = epoch_report.main(
        ["--bench", str(bench), "--baseline", str(wrapped)]
    )
    assert rc == 1  # 80% throughput drop vs the wrapped baseline


def test_empty_inputs_exit_3(epoch_report, tmp_path):
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"traceEvents": []}))
    rc = epoch_report.main(["--trace", str(empty)])
    assert rc == 3


# ---------------------------------------------------------------------------
# Temporal-plane joins (ISSUE 7)
# ---------------------------------------------------------------------------


def _ndjson(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def test_absent_temporal_artifact_is_informational(
    epoch_report, tmp_path, capsys
):
    """A temporal artifact that was never produced (path absent — the
    plane was off) is a NOTE, not a failure: the report still exits 0
    on otherwise-good inputs."""
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps({"value": 1.0, "stall_pct": 1.0}))
    rc = epoch_report.main(
        [
            "--bench", str(bench),
            "--events", str(tmp_path / "never-written"),
            "--task-records", str(tmp_path / "also-never"),
            "--timeseries", str(tmp_path / "nope"),
        ]
    )
    err = capsys.readouterr().err
    assert rc == 0
    assert "informational" in err and "no events present" in err


def test_present_but_empty_temporal_artifact_exits_3(
    epoch_report, tmp_path, capsys
):
    """The zero-coverage rule: an events spool that exists but holds
    zero records means the plane was ON and recorded nothing — that
    must not gate green, even when the bench numbers look fine."""
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps({"value": 1.0, "stall_pct": 1.0}))
    spool = tmp_path / "events"
    spool.mkdir()
    _ndjson(str(spool / "events-123.ndjson"), [])
    rc = epoch_report.main(
        ["--bench", str(bench), "--events", str(spool)]
    )
    err = capsys.readouterr().err
    assert rc == 3
    assert "present but empty" in err


def test_events_join_and_straggler_table(epoch_report, tmp_path, capsys):
    """Events fold into per-epoch retry/recovery counts + a notable
    list, and the task records render the per-epoch straggler table
    with the outlier flagged."""
    events_file = tmp_path / "events-1.ndjson"
    _ndjson(
        str(events_file),
        [
            {"ts": 10.0, "kind": "epoch.start", "epoch": 0},
            {"ts": 11.0, "kind": "stage.retry", "epoch": 0,
             "stage": "map", "attempt": 1},
            {"ts": 12.0, "kind": "recovery", "epoch": 0,
             "counter": "recovery.rematerialized"},
            {"ts": 13.0, "kind": "epoch.done", "epoch": 0},
        ],
    )
    tasks_file = tmp_path / "tasks-1.ndjson"
    _ndjson(
        str(tasks_file),
        [
            {"ts": 10.0, "stage": "reduce", "host": "hA", "pid": 1,
             "epoch": 0, "dur_s": 0.2},
            {"ts": 10.5, "stage": "reduce", "host": "hA", "pid": 1,
             "epoch": 0, "dur_s": 0.25},
            {"ts": 11.0, "stage": "reduce", "host": "hB", "pid": 2,
             "epoch": 0, "dur_s": 0.21},
            {"ts": 12.0, "stage": "reduce", "host": "hB", "pid": 2,
             "epoch": 0, "dur_s": 5.0},
        ],
    )
    rc = epoch_report.main(
        [
            "--events", str(events_file),
            "--task-records", str(tasks_file),
            "--json",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    report = json.loads(out)
    assert report["header"]["events_by_kind"]["stage.retry"] == 1
    row = next(r for r in report["epochs"] if r["epoch"] == 0)
    assert row["retries"] == 1 and row["recoveries"] == 1
    srow = report["stragglers"][0]
    assert srow["stage"] == "reduce" and srow["tasks"] == 4
    assert srow["flagged"] == 1
    assert srow["slowest_host"] == "hB"
    assert any(e["kind"] == "stage.retry" for e in report["events"])

    # The rendered table names the straggler too.
    rc = epoch_report.main(
        ["--events", str(events_file), "--task-records", str(tasks_file)]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "straggler table" in out and "STRAGGLER:" in out
    assert "notable events" in out


def test_timeseries_summary_in_header(epoch_report, tmp_path, capsys):
    ts_file = tmp_path / "timeseries.ndjson"
    _ndjson(
        str(ts_file),
        [
            {"ts": 100.0, "dt": None, "metrics": {
                "shuffle.map_rows": {"kind": "counter", "value": 10.0}}},
            {"ts": 102.0, "dt": 2.0, "metrics": {
                "shuffle.map_rows": {"kind": "counter", "value": 30.0,
                                     "rate": 10.0}}},
        ],
    )
    rc = epoch_report.main(["--timeseries", str(ts_file), "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    header = json.loads(out)["header"]
    assert header["timeseries"]["samples"] == 2
    assert header["timeseries"]["span_s"] == 2.0
    assert header["timeseries"]["map_rows_rate"]["max"] == 10.0
