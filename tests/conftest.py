"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so that every sharding /
multi-chip code path executes without TPU hardware (the driver separately
dry-runs the multi-chip path; see ``__graft_entry__.dryrun_multichip``).
The env vars must be set before the first ``import jax`` anywhere in the
test process, hence the top-of-module placement.

The reference's fixture analog: a single 1-CPU local Ray instance standing
in for the cluster (``tests/conftest.py:7-44`` in the reference).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin (when present) overrides JAX_PLATFORMS from the
# environment; the config API takes precedence, so force CPU explicitly.
import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from ray_shuffling_data_loader_tpu import runtime


@pytest.fixture(scope="module")
def local_runtime():
    """Module-scoped runtime session (analog of the reference's module-scoped
    ``ray_start_regular_shared`` fixture)."""
    ctx = runtime.init(num_workers=2)
    yield ctx
    runtime.shutdown()
