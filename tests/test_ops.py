"""Pallas dot-interaction kernel tests (interpreter mode on CPU): forward
parity with the XLA reference, tail-tile padding, gradient correctness of
the custom VJP, and jit/vmap composition."""

import numpy as np
import pytest

from jax_compat import needs_sharding_rule

import jax
import jax.numpy as jnp

from ray_shuffling_data_loader_tpu.ops import (
    dot_interaction,
    dot_interaction_reference,
    num_pairs,
)


def _rand(b, n, d, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((b, n, d)), dtype=dtype)


def test_num_pairs():
    assert num_pairs(19) == 171
    assert num_pairs(2) == 1


def test_reference_matches_manual():
    x = _rand(4, 5, 8)
    out = dot_interaction_reference(x)
    assert out.shape == (4, num_pairs(5))
    manual = []
    xn = np.asarray(x)
    for i in range(5):
        for j in range(i + 1, 5):
            manual.append((xn[:, i] * xn[:, j]).sum(-1))
    np.testing.assert_allclose(
        np.asarray(out), np.stack(manual, axis=1), rtol=1e-5
    )


@needs_sharding_rule
@pytest.mark.parametrize("b,block", [(8, 8), (10, 4), (3, 256)])
def test_pallas_forward_parity(b, block):
    """Kernel (interpreted) == reference, including ragged tail tiles."""
    x = _rand(b, 7, 16, seed=b)
    got = dot_interaction(
        x, use_pallas=True, block_batch=block, interpret=True
    )
    want = dot_interaction_reference(x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


@needs_sharding_rule
def test_pallas_grad_matches_reference():
    x = _rand(6, 5, 8, seed=42)

    def loss_pallas(x):
        return jnp.sum(
            dot_interaction(
                x, use_pallas=True, block_batch=4, interpret=True
            )
            ** 2
        )

    def loss_ref(x):
        return jnp.sum(dot_interaction_reference(x) ** 2)

    g_pallas = jax.grad(loss_pallas)(x)
    g_ref = jax.grad(loss_ref)(x)
    np.testing.assert_allclose(
        np.asarray(g_pallas), np.asarray(g_ref), rtol=1e-4, atol=1e-4
    )


@needs_sharding_rule
def test_pallas_under_jit():
    x = _rand(5, 6, 4, seed=7)

    @jax.jit
    def f(x):
        return dot_interaction(
            x, use_pallas=True, block_batch=8, interpret=True
        )

    np.testing.assert_allclose(
        np.asarray(f(x)),
        np.asarray(dot_interaction_reference(x)),
        rtol=1e-5,
        atol=1e-5,
    )


def test_auto_policy_off_tpu_uses_reference():
    # On the CPU test backend, auto must pick the reference path (no Mosaic).
    x = _rand(2, 4, 4)
    out = dot_interaction(x)  # would raise if it tried to lower Mosaic on CPU
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dot_interaction_reference(x)), rtol=1e-5
    )


def test_model_uses_interaction(local_runtime):
    """The flagship DLRM's forward equals a manual recomputation through the
    reference interaction — guards the model/op integration point."""
    from ray_shuffling_data_loader_tpu.models import TabularDLRM

    model = TabularDLRM(
        vocab_sizes={"a": 16, "b": 16, "c": 16}, embed_dim=8, top_mlp=(16,)
    )
    feats = {
        k: jnp.asarray(np.arange(4) % 16, jnp.int32) for k in ("a", "b", "c")
    }
    params = model.init(jax.random.key(0), feats)
    out = model.apply(params, feats)
    assert out.shape == (4,)
    assert np.isfinite(np.asarray(out)).all()
