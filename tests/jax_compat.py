"""Feature probes for seed-level JAX API gaps (shared by the kernel test
modules).

The seed's kernel code targets a newer JAX surface than the pinned
toolchain (0.4.37) provides; the affected tests fail at import/trace time
with the SAME two errors every run, burying real regressions in known
noise. Each probe detects the actual API (not a version string compare),
so the gates lift themselves the moment the toolchain moves.

Tracking note (seed-level, present since the v0 seed — see CHANGES.md):

* ``jax.shard_map`` — top-level export added after 0.4.x; 0.4.37 only
  has ``jax.experimental.shard_map``. RESOLVED (PR 3): kernel call
  sites go through ``ray_shuffling_data_loader_tpu.jax_compat
  .shard_map``, which is the top-level surface when present and the
  experimental one (``check_vma`` mapped to ``check_rep``) otherwise —
  the probe below accepts either, so the gate lifts on 0.4.37.
* ``custom_partitioning.def_partition(sharding_rule=...)`` — the
  Shardy-style rule argument landed in jax 0.4.38. Used by
  ``ops/interaction.py`` (and through it the flash-attention custom
  partitioning). Still gated: 0.4.37 has no equivalent to shim.
"""

import inspect

import jax
import pytest

try:
    from jax.experimental.shard_map import shard_map as _experimental_sm

    _HAS_EXPERIMENTAL_SHARD_MAP = _experimental_sm is not None
except Exception:  # pragma: no cover — probe only
    _HAS_EXPERIMENTAL_SHARD_MAP = False

# Either surface satisfies the kernels now that call sites route through
# the jax_compat shim.
HAS_TOPLEVEL_SHARD_MAP = (
    hasattr(jax, "shard_map") or _HAS_EXPERIMENTAL_SHARD_MAP
)

try:
    from jax.experimental.custom_partitioning import custom_partitioning

    HAS_SHARDING_RULE = "sharding_rule" in inspect.signature(
        custom_partitioning.def_partition
    ).parameters
except Exception:  # pragma: no cover — probe only
    HAS_SHARDING_RULE = False

needs_toplevel_shard_map = pytest.mark.skipif(
    not HAS_TOPLEVEL_SHARD_MAP,
    reason="seed-level gap on jax<=0.4.37: no top-level jax.shard_map "
    "(only jax.experimental.shard_map); see tests/jax_compat.py tracking "
    "note",
)

needs_sharding_rule = pytest.mark.skipif(
    not HAS_SHARDING_RULE,
    reason="seed-level gap on jax<=0.4.37: custom_partitioning"
    ".def_partition() lacks sharding_rule= (added in jax 0.4.38); see "
    "tests/jax_compat.py tracking note",
)

needs_kernel_partitioning_apis = pytest.mark.skipif(
    not (HAS_TOPLEVEL_SHARD_MAP and HAS_SHARDING_RULE),
    reason="seed-level gap on jax<=0.4.37: needs jax.shard_map AND "
    "custom_partitioning sharding_rule=; see tests/jax_compat.py "
    "tracking note",
)
