"""Straggler/skew-attribution tests (ISSUE 7): the analysis fold
(median/p99/skew, slowest-host attribution, flagged outliers), the
wedged-worker flag from the in-flight feed, the straggler.* gauges,
and the chaos integration — a ``wedge`` fault injected into a reduce
task must be flagged by the live detector, appear in ``/status``, and
land in the epoch-report straggler table (function-scoped runtimes,
per the obs/chaos test convention)."""

import json
import os
import threading
import time
import urllib.request

import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.runtime import faults
from ray_shuffling_data_loader_tpu.telemetry import metrics, stragglers

_ENV = (
    "RSDL_METRICS", "RSDL_METRICS_DIR", "RSDL_OBS_PORT",
    "RSDL_FAULTS", "RSDL_FAULTS_SEED", "RSDL_FAULTS_WEDGE_S",
    "RSDL_STRAGGLER_K", "RSDL_STRAGGLER_MIN_S",
    "RSDL_AUDIT", "RSDL_AUDIT_DIR",
)


@pytest.fixture
def straggler_env(tmp_path):
    saved = {k: os.environ.get(k) for k in _ENV}
    spool = str(tmp_path / "metrics-spool")
    os.environ["RSDL_METRICS"] = "1"
    os.environ["RSDL_METRICS_DIR"] = spool
    for k in _ENV[2:]:
        os.environ.pop(k, None)
    metrics.refresh_from_env()
    metrics.reset()
    stragglers.reset(clear_spool=True)
    yield spool
    stragglers.reset(clear_spool=True)
    metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    metrics.refresh_from_env()
    faults.refresh_from_env()


def _rec(stage, dur, host="hostA", pid=1, epoch=0, ts=None):
    return {
        "ts": ts if ts is not None else time.time(),
        "stage": stage, "host": host, "pid": pid,
        "epoch": epoch, "dur_s": dur,
    }


def test_analyze_skew_and_slowest_host(straggler_env):
    records = (
        [_rec("reduce", 0.1, host="hostA") for _ in range(8)]
        + [_rec("reduce", 0.12, host="hostB") for _ in range(7)]
        + [_rec("reduce", 6.0, host="hostB")]  # the outlier
        + [_rec("map", 0.05) for _ in range(4)]
    )
    analysis = stragglers.analyze(records=records, in_flight=[])
    reduce_st = analysis["stages"]["reduce"]
    assert reduce_st["count"] == 16
    assert reduce_st["median_s"] == pytest.approx(0.12, abs=0.02)
    assert reduce_st["p99_s"] == pytest.approx(6.0)
    assert reduce_st["skew_ratio"] > 10
    assert reduce_st["slowest_host"] == "hostB"
    # One flagged outlier: 6.0 > max(1.0, 4 x 0.12); the true count is
    # carried separately from the (capped) sample rows.
    assert [t["dur_s"] for t in reduce_st["flagged"]] == [6.0]
    assert reduce_st["flagged_total"] == 1
    assert analysis["flagged_total"] == 1
    assert analysis["flagged"][0]["stage"] == "reduce"
    assert analysis["wedged"] == []
    # Fast, even stages flag nothing (floor keeps tiny medians sane).
    assert analysis["stages"]["map"]["flagged"] == []


def test_wedged_from_inflight_feed(straggler_env):
    records = [_rec("reduce", 0.1) for _ in range(8)]
    in_flight = [
        {"stage": "shuffle_reduce", "pid": 999, "age_s": 30.0},
        {"stage": "shuffle_reduce", "pid": 1000, "age_s": 0.05},
    ]
    analysis = stragglers.analyze(records=records, in_flight=in_flight)
    assert len(analysis["wedged"]) == 1
    wedged = analysis["wedged"][0]
    # Task-fn names canonicalize to stage names.
    assert wedged["stage"] == "reduce" and wedged["pid"] == 999
    assert wedged["age_s"] == pytest.approx(30.0)


def test_record_task_spool_roundtrip(straggler_env):
    stragglers.record_task("shuffle_map", 0.25, epoch=3)
    stragglers.flush()
    files = os.listdir(stragglers.spool_dir())
    assert files == [f"tasks-{os.getpid()}.ndjson"]
    recs = stragglers.load_records()
    assert len(recs) == 1
    assert recs[0]["stage"] == "map" and recs[0]["epoch"] == 3
    # The cumulative histogram rode the registry too.
    snap = metrics.registry.snapshot()
    assert snap["task.duration_seconds{stage=map}_count"] == 1.0


def test_load_records_tail_read_sees_appends(straggler_env):
    """The live-spool read is incremental (append-only files tail-read
    from the last offset) — records appended after a first load must
    still appear in the next one."""
    stragglers.record_task("shuffle_map", 0.1, epoch=0)
    stragglers.flush()
    assert len(stragglers.load_records()) == 1
    stragglers.record_task("shuffle_map", 0.2, epoch=0)
    stragglers.flush()
    recs = stragglers.load_records()
    assert sorted(r["dur_s"] for r in recs) == [0.1, 0.2]
    # Unchanged files are served from cache (same result, no re-parse).
    assert len(stragglers.load_records()) == 2


def test_publish_metrics_gauges(straggler_env):
    records = [_rec("reduce", 0.5) for _ in range(4)] + [
        _rec("reduce", 9.0)
    ]
    analysis = stragglers.analyze(records=records, in_flight=[
        {"stage": "shuffle_reduce", "pid": 7, "age_s": 60.0}
    ])
    stragglers.publish_metrics(analysis)
    snap = metrics.registry.snapshot()
    assert snap["straggler.p99_seconds{stage=reduce}"] == pytest.approx(9.0)
    assert snap["straggler.flagged_tasks{stage=reduce}"] == 1.0
    assert snap["straggler.wedged_tasks"] == 1.0


# ---------------------------------------------------------------------------
# Chaos integration: a wedged worker is caught live and post-hoc
# ---------------------------------------------------------------------------

NUM_FILES = 2
ROWS_PER_FILE = 512
NUM_REDUCERS = 4


def test_chaos_wedge_flagged_live_and_in_report(
    straggler_env, tmp_path, capsys
):
    """Arm a deterministic ``wedge`` fault on one reduce task: while it
    sleeps, the in-flight detector must flag the wedged worker (live,
    visible in /status); after completion the task lands as a flagged
    outlier; and the epoch-report straggler table renders it."""
    from ray_shuffling_data_loader_tpu.data_generation import generate_file
    from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle
    from ray_shuffling_data_loader_tpu.telemetry import audit, obs_server

    os.environ["RSDL_FAULTS"] = "task.reduce/task:wedge:1x1"
    os.environ["RSDL_FAULTS_SEED"] = "42"
    os.environ["RSDL_FAULTS_WEDGE_S"] = "2.5"
    faults.refresh_from_env()
    # The audit plane rides along (ISSUE 7 acceptance): the wedge must
    # be flagged with exactly-once delivery intact.
    audit.enable(spool_dir=str(tmp_path / "audit-spool"))
    # One worker process: the x1 cap is per process, so exactly one
    # reduce task wedges and the other three stay fast.
    ctx = runtime.init(num_workers=1)
    port = obs_server.start(0)
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    files = [
        generate_file(i, i * ROWS_PER_FILE, ROWS_PER_FILE, 1,
                      str(data_dir))[0]
        for i in range(NUM_FILES)
    ]

    class _Consumer(BatchConsumer):
        def __init__(self):
            self.done = threading.Event()

        def consume(self, rank, epoch, batches):
            pass

        def producer_done(self, rank, epoch):
            self.done.set()

        def wait_until_ready(self, epoch):
            pass

        def wait_until_all_epochs_done(self):
            assert self.done.wait(timeout=180)

    errors = []

    def _run():
        try:
            shuffle(
                files, _Consumer(), num_epochs=1,
                num_reducers=NUM_REDUCERS, num_trainers=1, seed=3,
            )
        except BaseException as exc:
            errors.append(exc)

    thread = threading.Thread(target=_run, daemon=True)
    thread.start()
    try:
        # Live: poll until the wedged in-flight task is flagged — both
        # by the detector and on the /status page.
        wedged_live = status_wedged = None
        deadline = time.time() + 120
        while time.time() < deadline:
            analysis = stragglers.analyze()
            if analysis["wedged"]:
                wedged_live = analysis["wedged"][0]
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=10
                ) as resp:
                    status = json.loads(resp.read().decode())
                if status.get("stragglers", {}).get("wedged"):
                    status_wedged = status["stragglers"]["wedged"][0]
                    break
            time.sleep(0.05)
        assert wedged_live is not None, "wedged worker never flagged live"
        assert wedged_live["stage"] == "reduce"
        assert status_wedged is not None, "/status never showed it"
        thread.join(timeout=180)
        assert not thread.is_alive()
        assert not errors, errors
        # Post-hoc: the wedged task completed ~2.5 s slow and is now a
        # flagged outlier with correct stage attribution.
        analysis = stragglers.analyze()
        flagged = [
            t for t in analysis["flagged"] if t["stage"] == "reduce"
        ]
        assert flagged and flagged[0]["dur_s"] >= 2.0
        assert analysis["stages"]["reduce"]["skew_ratio"] is None or (
            analysis["stages"]["reduce"]["skew_ratio"] > 2
        )
        # Audit ok=true throughout: the wedge slowed the epoch, it did
        # not drop or duplicate a row.
        assert audit.summary().get("ok") is True
    finally:
        obs_server.stop()
        runtime.shutdown()
        audit.disable()
        audit.reset(clear_spool=True)
        audit.refresh_from_env()

    # The epoch report renders the straggler table from the spool.
    import importlib.util

    tool_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "epoch_report.py",
    )
    spec = importlib.util.spec_from_file_location("epoch_report", tool_path)
    epoch_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(epoch_report)
    rc = epoch_report.main(
        ["--task-records", stragglers.spool_dir(), "--json"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    report = json.loads(out)
    reduce_rows = [
        r for r in report["stragglers"] if r["stage"] == "reduce"
    ]
    assert reduce_rows and reduce_rows[0]["flagged"] >= 1
    assert reduce_rows[0]["tasks"] == NUM_REDUCERS
