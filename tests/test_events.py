"""Structured event-log tests (ISSUE 7): emit/flush/load/counts, trace
context riding the records, the metrics-off no-op facade, and the
flush-before-task-done ordering — a resolved task future implies the
worker's events are already on the spool (function-scoped runtime, per
the obs/chaos test convention)."""

import os
import time

import pytest

from ray_shuffling_data_loader_tpu import runtime, telemetry
from ray_shuffling_data_loader_tpu.telemetry import events, metrics

_ENV = ("RSDL_METRICS", "RSDL_METRICS_DIR", "RSDL_EVENTS_DIR",
        "RSDL_OBS_PORT")


@pytest.fixture
def events_env(tmp_path):
    saved = {k: os.environ.get(k) for k in _ENV}
    spool = str(tmp_path / "events-spool")
    os.environ["RSDL_METRICS"] = "1"
    os.environ["RSDL_METRICS_DIR"] = str(tmp_path / "metrics-spool")
    os.environ["RSDL_EVENTS_DIR"] = spool
    os.environ.pop("RSDL_OBS_PORT", None)
    metrics.refresh_from_env()
    metrics.reset()
    events.reset(clear_spool=True)
    yield spool
    events.reset(clear_spool=True)
    metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    metrics.refresh_from_env()


def test_emit_flush_load_counts(events_env):
    events.emit("epoch.start", epoch=0, schedule="mapreduce")
    events.emit("epoch.done", epoch=0)
    events.emit("stage.retry", epoch=0, stage="map", attempt=1)
    # Buffered records are visible without a flush (same-process load).
    loaded = events.load()
    assert [r["kind"] for r in loaded] == [
        "epoch.start", "epoch.done", "stage.retry"
    ]
    events.flush()
    fnames = os.listdir(events_env)
    assert fnames == [f"events-{os.getpid()}.ndjson"]
    # Spooled records load identically; identity stamped.
    loaded = events.load()
    assert len(loaded) == 3
    assert loaded[0]["pid"] == os.getpid()
    assert loaded[0]["role"] == "driver"
    assert loaded[0]["schedule"] == "mapreduce"
    assert events.counts() == {
        "epoch.start": 1, "epoch.done": 1, "stage.retry": 1
    }


def test_load_filters(events_env):
    t0 = time.time()
    events.emit("a.one")
    events.emit("a.two")
    events.emit("a.two")
    assert [r["kind"] for r in events.load(kind="a.two")] == [
        "a.two", "a.two"
    ]
    assert len(events.load(since=t0 - 1)) == 3
    assert events.load(since=time.time() + 60) == []
    assert len(events.load(limit=2)) == 2


def test_trace_context_rides_records(events_env):
    with telemetry.context(trial=1, epoch=5):
        events.emit("epoch.start")
        # Explicit fields win over ambient context.
        events.emit("epoch.start", epoch=6)
    first, second = events.load()
    assert first["trial"] == 1 and first["epoch"] == 5
    assert second["epoch"] == 6


def test_facade_noop_when_metrics_off(events_env):
    metrics.disable()
    telemetry.emit_event("should.not.appear")
    events.emit("also.should.not.appear")
    metrics.enable()
    metrics.refresh_from_env()
    assert events.load() == []
    assert not os.path.isdir(events_env) or not os.listdir(events_env)


def _emitting_task(payload):
    """Worker-side task body: emits an event, does NOT flush — the
    task-done path must."""
    from ray_shuffling_data_loader_tpu import telemetry as t

    t.emit_event("test.worker_event", payload=payload)
    return payload * 2


def test_event_flush_before_task_done(events_env, tmp_path):
    """The ordering contract: by the time a task future resolves, the
    worker's events are on the spool — no sleep, no polling."""
    ctx = runtime.init(num_workers=1)
    try:
        fut = ctx.pool.submit(_emitting_task, 21)
        assert fut.result(timeout=120) == 42
        # Immediately after the result is observable, the record is
        # loadable from the spool (written by the worker pid).
        recs = events.load(kind="test.worker_event")
        assert len(recs) == 1
        assert recs[0]["payload"] == 21
        assert recs[0]["pid"] != os.getpid()
        assert recs[0]["role"] == "task"
    finally:
        runtime.shutdown()


def test_torn_tail_line_skipped(events_env):
    events.emit("whole.record")
    events.flush()
    path = os.path.join(events_env, f"events-{os.getpid()}.ndjson")
    with open(path, "a") as f:
        f.write('{"kind": "torn.rec')  # a crash mid-append
    loaded = events.load()
    assert [r["kind"] for r in loaded] == ["whole.record"]
