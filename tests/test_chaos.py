"""Chaos harness: end-to-end shuffles under scripted fault schedules.

The PR-3 contract: every injected failure class is *recovered*, and the
recovery is *proven correct* — each run ends with the PR-2 audit layer's
epoch digests reconciling map == reduce == delivered (``RSDL_AUDIT=1``,
strict mode, so a mismatch raises instead of logging). Fault schedules
ride ``RSDL_FAULTS`` with a fixed ``RSDL_FAULTS_SEED``, so every run
here replays the same deterministic schedule (``runtime/faults.py``).

Covered failure classes (ISSUE 3 acceptance):

* crashed map task (entry-point crash; re-executed within budget),
* crashed reduce task (exit-point crash; re-executed, audit dedup
  absorbs the duplicate digest records),
* lost store object (reduce input vanishes; lineage re-executes the
  producing map and retries the reduce),
* transport reset (pre-send connection reset; the actor client's
  bounded reconnect-retry rides it out),
* killed host agent (scheduler failover onto the surviving agent),
* dead queue producer (consumer unblocks with ``ProducerDiedError``
  and a fresh driver re-runs the epoch deterministically),

plus the negative case: a poison task (crashes on *every* attempt)
exhausts its budget and fails the epoch with a structured
``StageFailedError`` instead of retrying forever.
"""

import collections
import os
import signal
import subprocess
import sys
import time

import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.batch_queue import (
    BatchQueue,
    ProducerDiedError,
)
from ray_shuffling_data_loader_tpu.data_generation import generate_file
from ray_shuffling_data_loader_tpu.runtime import faults
from ray_shuffling_data_loader_tpu.shuffle import (
    BatchConsumer,
    StageFailedError,
    shuffle,
)
from ray_shuffling_data_loader_tpu.telemetry import audit as _audit
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NUM_FILES = 4
ROWS_PER_FILE = 400
TOTAL_ROWS = NUM_FILES * ROWS_PER_FILE


@pytest.fixture(scope="module")
def chaos_files(tmp_path_factory):
    """Small Parquet dataset written IN-PROCESS (no worker pool): the
    per-test runtimes below must spawn their pools *after* the fault
    schedule is armed, so nothing here may touch the runtime."""
    data_dir = tmp_path_factory.mktemp("chaos-data")
    files = []
    for i in range(NUM_FILES):
        fname, _ = generate_file(
            i, i * ROWS_PER_FILE, ROWS_PER_FILE, 1, str(data_dir)
        )
        files.append(fname)
    return files


@pytest.fixture
def chaos_env(monkeypatch, tmp_path):
    """Arm audit (strict) + metrics + a fault schedule, then bring up a
    fresh runtime whose spawned workers inherit all three via the
    environment. Function-scoped on purpose: fault schedules are parsed
    once per process, so every test needs its own worker pool."""
    started = []

    def arm(spec: str, seed: int = 0, extra_env=None):
        spool = tmp_path / "audit-spool"
        spool.mkdir(exist_ok=True)
        monkeypatch.setenv("RSDL_AUDIT", "1")
        monkeypatch.setenv("RSDL_AUDIT_STRICT", "1")
        monkeypatch.setenv("RSDL_AUDIT_DIR", str(spool))
        monkeypatch.setenv("RSDL_METRICS", "1")
        if spec:
            monkeypatch.setenv("RSDL_FAULTS", spec)
        else:
            monkeypatch.delenv("RSDL_FAULTS", raising=False)
        monkeypatch.setenv("RSDL_FAULTS_SEED", str(seed))
        for k, v in (extra_env or {}).items():
            monkeypatch.setenv(k, v)
        _audit.refresh_from_env()
        _metrics.refresh_from_env()
        _metrics.registry.clear()
        faults.refresh_from_env()
        ctx = runtime.init(num_workers=2)
        started.append(ctx)
        return ctx

    yield arm
    runtime.shutdown()
    monkeypatch.undo()
    _audit.reset()
    _audit.refresh_from_env()
    _metrics.refresh_from_env()
    faults.refresh_from_env()


class CollectingConsumer(BatchConsumer):
    def __init__(self):
        self.keys = collections.defaultdict(list)
        self.done = collections.defaultdict(bool)

    def consume(self, rank, epoch, batches):
        store = runtime.get_context().store
        for ref in batches:
            cb = store.get_columns(ref)
            self.keys[(epoch, rank)].extend(cb["key"].tolist())
            store.free(ref)

    def producer_done(self, rank, epoch):
        self.done[(epoch, rank)] = True

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


def _run_audited_shuffle(files, **kw):
    consumer = CollectingConsumer()
    shuffle(files, consumer, **kw)
    return consumer


def _assert_exactly_once(consumer, epoch, num_trainers=1):
    keys = []
    for rank in range(num_trainers):
        assert consumer.done[(epoch, rank)]
        keys.extend(consumer.keys[(epoch, rank)])
    assert sorted(keys) == list(range(TOTAL_ROWS))


def _counter(name_prefix: str) -> float:
    snap = _metrics.registry.snapshot()
    return sum(v for k, v in snap.items() if k.startswith(name_prefix))


# ---------------------------------------------------------------------------
# Fault-plane unit behavior (determinism, filters, zero overhead)
# ---------------------------------------------------------------------------


def test_faults_off_is_noop(monkeypatch):
    monkeypatch.delenv("RSDL_FAULTS", raising=False)
    faults.refresh_from_env()
    assert not faults.enabled()
    assert faults.should_fire("any.site") is None
    assert faults.fired_counts() == {}


def test_fault_schedule_is_deterministic(monkeypatch):
    monkeypatch.setenv("RSDL_FAULTS", "x.y:crash:0.3")
    monkeypatch.setenv("RSDL_FAULTS_SEED", "42")
    faults.refresh_from_env()
    seq1 = [faults.should_fire("x.y") for _ in range(64)]
    faults.refresh_from_env()  # same env -> same schedule
    seq2 = [faults.should_fire("x.y") for _ in range(64)]
    assert seq1 == seq2
    assert "crash" in seq1 and None in seq1  # ~30% firing rate
    monkeypatch.setenv("RSDL_FAULTS_SEED", "43")
    faults.refresh_from_env()
    seq3 = [faults.should_fire("x.y") for _ in range(64)]
    assert seq3 != seq1  # different seed, different schedule
    faults.refresh_from_env()


def test_fault_filters(monkeypatch):
    monkeypatch.setenv(
        "RSDL_FAULTS", "a.b/task:crash:1.0,c.d:crash:1.0@2,e.f:crash:1x1"
    )
    faults.refresh_from_env()
    # role filter: this process is role "driver".
    assert faults.should_fire("a.b") is None
    faults.set_role("task")
    try:
        assert faults.should_fire("a.b") == "crash"
    finally:
        faults.set_role("driver")
    # epoch filter
    assert faults.should_fire("c.d", epoch=1) is None
    assert faults.should_fire("c.d", epoch=2) == "crash"
    # max-fires cap
    assert faults.should_fire("e.f") == "crash"
    assert faults.should_fire("e.f") is None
    assert faults.fired_counts()[("e.f", "crash")] == 1
    faults.refresh_from_env()


def test_fault_entry_exit_points(monkeypatch):
    monkeypatch.setenv("RSDL_FAULTS", "t.s:crash-exit:1.0")
    faults.refresh_from_env()
    assert faults.should_fire("t.s", point="entry") is None
    assert faults.should_fire("t.s", point="exit") == "crash"
    faults.refresh_from_env()


def test_retry_policy_deadline_bounds_total_time():
    from ray_shuffling_data_loader_tpu.runtime.retry import RetryPolicy

    policy = RetryPolicy(
        max_attempts=50, base_delay_s=0.05, max_delay_s=0.05,
        multiplier=1.0, jitter=0.0, deadline_s=0.2,
    )
    start = time.monotonic()
    last = 0
    for attempt, backoff in policy.attempts("t"):
        last = attempt
        backoff.backoff("still failing")
    # The deadline, not the attempt budget, ended the loop — and well
    # before 50 x 50 ms of sleeping.
    assert last < 50
    assert time.monotonic() - start < 2.0


def test_producer_liveness_interval_clamped(monkeypatch):
    from ray_shuffling_data_loader_tpu import batch_queue as bq

    monkeypatch.setenv("RSDL_PRODUCER_LIVENESS_S", "0")
    assert bq._liveness_interval_s() == 0.05  # no busy-spin
    monkeypatch.setenv("RSDL_PRODUCER_LIVENESS_S", "-3")
    assert bq._liveness_interval_s() == 0.05
    monkeypatch.setenv("RSDL_PRODUCER_LIVENESS_S", "1.5")
    assert bq._liveness_interval_s() == 1.5


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError):
        faults.parse_spec("nonsense")
    with pytest.raises(ValueError):
        faults.parse_spec("a.b:frobnicate:0.5")
    with pytest.raises(ValueError):
        faults.parse_spec("a.b:crash:1.5")


# ---------------------------------------------------------------------------
# End-to-end recovery, proven by audit digests
# ---------------------------------------------------------------------------


def test_recovers_crashed_map_task(chaos_files, chaos_env):
    chaos_env("task.map:crash-entry:1x1", seed=11)
    consumer = _run_audited_shuffle(
        chaos_files, num_epochs=1, num_reducers=4, num_trainers=1, seed=5
    )
    _assert_exactly_once(consumer, 0)
    summary = _audit.summary()
    assert summary["ok"] is True, summary
    assert _counter("recovery.stage_retries") >= 1


def test_recovers_crashed_reduce_task(chaos_files, chaos_env):
    # Exit-point crash: the reducer output and its audit digest are
    # already published when the task dies — the retry's duplicate
    # records are exactly what the reconciler's dedup exists for.
    chaos_env("task.reduce:crash-exit:1x1", seed=13)
    consumer = _run_audited_shuffle(
        chaos_files, num_epochs=1, num_reducers=4, num_trainers=1, seed=5
    )
    _assert_exactly_once(consumer, 0)
    summary = _audit.summary()
    assert summary["ok"] is True, summary
    assert _counter("recovery.stage_retries") >= 1


def test_recovers_lost_store_object(chaos_files, chaos_env):
    # The first store.get in each pool worker reports its object lost
    # (the reduce's first input partition). The driver must re-execute
    # the producing map from lineage and retry the reduce.
    chaos_env("store.get/task:lost:1x1", seed=17)
    consumer = _run_audited_shuffle(
        chaos_files, num_epochs=1, num_reducers=4, num_trainers=1, seed=5
    )
    _assert_exactly_once(consumer, 0)
    summary = _audit.summary()
    assert summary["ok"] is True, summary
    assert _counter("recovery.rematerialized") >= 1


def test_recovers_lost_decode_cache_index_schedule(chaos_files, chaos_env):
    """Index schedule: a lost decode-cache segment is never in the
    partition lineage, so its recovery path is cache *regeneration* —
    re-decode the file from Parquet, republish, and swap the new ref
    into the epoch's cache list and the cross-epoch registry. A lost
    cache must cost one re-decode, not the epoch."""
    # The package re-exports the shuffle FUNCTION under the module's
    # name, so plain import forms bind the function; go via sys.modules.
    import importlib

    shuffle_mod = importlib.import_module(
        "ray_shuffling_data_loader_tpu.shuffle"
    )

    ctx = chaos_env("", seed=0, extra_env={"RSDL_INDEX_SHUFFLE": "on"})
    _audit.begin_run()
    cache = shuffle_mod._DecodeCache(enabled=True)
    cache_refs = []
    for i, fname in enumerate(chaos_files):
        refs, cref = shuffle_mod.shuffle_map(
            fname, i, 4, epoch=0, seed=5, publish_cache=True
        )
        ctx.store.free(refs)  # partitions unused; only the cache matters
        assert cref is not None
        cache.register(i, shuffle_mod._ResolvedMapResult((None, cref)))
        cache_refs.append(cref)
    # Lose one cache segment outright (as if the host holding the only
    # copy died) — the plan stage reading it must hit ObjectLostError.
    lost = cache_refs[1]
    path = ctx.store._find_segment(lost.object_id)
    assert path is not None
    os.unlink(path)

    consumer = CollectingConsumer()
    schedule_log = []
    thread = shuffle_mod.shuffle_epoch(
        0,
        chaos_files,
        consumer,
        num_reducers=4,
        num_trainers=1,
        seed=5,
        decode_cache=cache,
        schedule_log=schedule_log,
    )
    thread.join()
    assert thread.error is None, thread.error
    assert schedule_log == [(0, "index")]
    _assert_exactly_once(consumer, 0)
    assert _counter("recovery.rematerialized") >= 1
    verdicts = _audit.reconcile([0])
    assert verdicts and verdicts[0]["ok"] is True, verdicts
    cache.free_all()


def test_recovers_transport_reset(chaos_files, chaos_env):
    # Driver-side pre-send connection reset on the control plane (queue
    # actor RPC): the actor client reconnects and retries; the epoch
    # must complete with exactly-once delivery.
    from ray_shuffling_data_loader_tpu.dataset import ShufflingDataset

    chaos_env("transport.send/driver:reset:1x1", seed=19)
    ds = ShufflingDataset(
        chaos_files,
        num_epochs=1,
        num_trainers=1,
        batch_size=200,
        rank=0,
        num_reducers=4,
        seed=5,
        queue_name="chaos-reset-q",
    )
    ds.set_epoch(0)
    keys = sorted(k for b in ds for k in b["key"].tolist())
    assert keys == list(range(TOTAL_ROWS))
    summary = _audit.summary()
    assert summary["ok"] is True, summary
    assert _counter("recovery.retries") >= 1


def test_killed_host_agent_fails_over(chaos_files, chaos_env):
    """Two in-process host agents behind a ClusterScheduler; one is
    SIGKILLed (dead-but-listed, like a preempted TPU host). Every task
    that lands on it must fail over to the survivor, with the dead agent
    evicted — and the epoch's digests must still reconcile."""
    from ray_shuffling_data_loader_tpu.runtime import actor as actor_mod
    from ray_shuffling_data_loader_tpu.runtime.cluster import (
        ClusterScheduler,
        HostAgent,
    )

    ctx = chaos_env("", seed=0)
    agents = [
        actor_mod.spawn_actor(
            HostAgent,
            ctx.runtime_dir,
            1,
            None,
            runtime_dir=ctx.runtime_dir,
            daemon=False,
        )
        for _ in range(2)
    ]
    victim, survivor = agents
    os.kill(victim.pid, signal.SIGKILL)
    sched = ClusterScheduler(agents, width=2)

    class _FakeCluster:
        def scheduler(self):
            return sched

    ctx.cluster = _FakeCluster()
    try:
        consumer = _run_audited_shuffle(
            chaos_files, num_epochs=1, num_reducers=4, num_trainers=1,
            seed=5,
        )
        _assert_exactly_once(consumer, 0)
        summary = _audit.summary()
        assert summary["ok"] is True, summary
        assert sched.agent_addresses == {survivor.address}
    finally:
        ctx.cluster = None
        sched.shutdown()
        survivor.terminate(grace_period_s=2.0)


# ---------------------------------------------------------------------------
# Dead producer: bounded detection + deterministic epoch re-run
# ---------------------------------------------------------------------------


def test_dead_producer_raises_within_deadline(chaos_env):
    chaos_env("", seed=0, extra_env={"RSDL_PRODUCER_LIVENESS_S": "0.5"})
    stand_in = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)"]
    )
    q = BatchQueue(
        num_epochs=1, num_trainers=1, max_concurrent_epochs=1,
        name="chaos-dead-prod",
    )
    try:
        q.ready()
        q.actor.call("register_producer", stand_in.pid)
        stand_in.kill()
        stand_in.wait()
        start = time.monotonic()
        with pytest.raises(ProducerDiedError) as excinfo:
            q.get_batch(0, 0)
        assert time.monotonic() - start < 30  # bounded, not a hang
        assert excinfo.value.epoch == 0 and excinfo.value.rank == 0
        # get() is supervised the same way.
        with pytest.raises(ProducerDiedError):
            q.get(0, 0)
    finally:
        stand_in.kill()
        q.shutdown()


PRODUCER_SCRIPT = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.batch_queue import BatchQueue

runtime.init(address=os.environ["RSDL_RUNTIME_DIR"])
q = BatchQueue(
    num_epochs=1, num_trainers=1, max_concurrent_epochs=1,
    name="chaos-prod-q",
)
q.ready()
q.new_epoch(0)
print("READY", flush=True)
time.sleep(300)  # wedge mid-epoch until the test kills us
"""


def test_dead_producer_epoch_rerun_recovers(chaos_files, chaos_env):
    """End-to-end producer death: a separate driver process creates the
    delivery queue, admits epoch 0, and dies without producing. The
    consumer unblocks with ProducerDiedError (not a hang), and because
    the shuffle is deterministic per (seed, epoch), a fresh driver
    re-runs the epoch and delivers exactly-once — digests reconciled."""
    from ray_shuffling_data_loader_tpu.dataset import ShufflingDataset

    ctx = chaos_env("", seed=0, extra_env={"RSDL_PRODUCER_LIVENESS_S": "0.5"})
    env = dict(os.environ, RSDL_RUNTIME_DIR=ctx.runtime_dir)
    producer = subprocess.Popen(
        [sys.executable, "-c", PRODUCER_SCRIPT.format(repo=_REPO)],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        env=env,
        text=True,
    )
    try:
        assert producer.stdout.readline().strip() == "READY", (
            "producer failed to start"
        )
        consumer_q = BatchQueue(
            num_epochs=1, num_trainers=1, max_concurrent_epochs=1,
            name="chaos-prod-q", connect=True,
        )
        producer.kill()
        producer.wait()
        with pytest.raises(ProducerDiedError):
            consumer_q.get_batch(0, 0)
    finally:
        producer.kill()
        producer.wait()

    # Recovery: a fresh driver re-runs the epoch (same seed => same
    # permutation) and the consumer reads it to completion.
    ds = ShufflingDataset(
        chaos_files,
        num_epochs=1,
        num_trainers=1,
        batch_size=200,
        rank=0,
        num_reducers=4,
        seed=5,
        queue_name="chaos-prod-q2",
    )
    ds.set_epoch(0)
    keys = sorted(k for b in ds for k in b["key"].tolist())
    assert keys == list(range(TOTAL_ROWS))
    summary = _audit.summary()
    assert summary["ok"] is True, summary


# ---------------------------------------------------------------------------
# Poison task: bounded budget, structured failure
# ---------------------------------------------------------------------------


def test_poison_task_surfaces_stage_failed_error(chaos_files, chaos_env):
    chaos_env("task.map:crash-entry:1.0", seed=3)  # every attempt dies
    consumer = CollectingConsumer()
    with pytest.raises(StageFailedError) as excinfo:
        shuffle(
            chaos_files,
            consumer,
            num_epochs=1,
            num_reducers=2,
            num_trainers=1,
            seed=5,
        )
    assert excinfo.value.stage == "map"
    assert excinfo.value.epoch == 0
    assert excinfo.value.attempts >= 2
    assert "FaultInjected" in str(excinfo.value)
    # No hang: every rank still got its producer-done sentinel.
    assert consumer.done[(0, 0)]
