"""Thread-interleaving stress/soak tests.

The reference leans on Ray's actor serialization for concurrency safety
and ships no stress coverage (SURVEY §5 "race detection: none"). This
framework runs far more concurrent machinery — queue delivery threads,
consumer acks, epoch-window joins, replacement consumers — so these soak
tests drive the REAL components through seeded-random interleavings and
assert the two invariants every delivery path must keep:

* exactly-once: every produced item is consumed exactly once per epoch;
* liveness: the whole dance finishes under a deadline (no deadlock
  between the epoch-window join, producer-done events, and acks).

Randomness is seeded per test case so a failing interleaving replays.
"""

import os
import random
import threading
import time

import pytest

from ray_shuffling_data_loader_tpu.batch_queue import BatchQueue

pytestmark = pytest.mark.slow

DEADLINE_S = 120.0
# Soak depth: default 3 seeds per scenario; RSDL_STRESS_SEEDS=N widens
# the interleaving search (used by long idle-host soaks).
_N_SEEDS = int(os.environ.get("RSDL_STRESS_SEEDS", "3"))
_SEEDS = list(range(_N_SEEDS))


def _join_threads(threads, deadline_s=DEADLINE_S):
    end = time.monotonic() + deadline_s
    for t in threads:
        t.join(max(0.1, end - time.monotonic()))
    stuck = [t.name for t in threads if t.is_alive()]
    assert not stuck, f"threads wedged past {deadline_s}s deadline: {stuck}"


def _run_threads(threads, deadline_s=DEADLINE_S):
    for t in threads:
        t.start()
    _join_threads(threads, deadline_s)


@pytest.mark.parametrize("seed", _SEEDS)
def test_queue_soak_multi_rank_windowed(local_runtime, seed):
    """4 consumer threads x 6 epochs x window 2, producer jitter vs
    consumer jitter, batched and single puts interleaved. Exercises the
    new_epoch window join racing producer_done events and task_done acks
    from four client threads at once.

    Each thread draws from its OWN Random derived from (seed, role): a
    shared instance would make per-thread draw sequences depend on OS
    scheduling (and random.Random is not thread-safe), defeating the
    replay-a-failing-seed design."""
    num_trainers, num_epochs, window = 4, 6, 2
    items_per_rank = 12
    q = BatchQueue(
        num_epochs=num_epochs,
        num_trainers=num_trainers,
        max_concurrent_epochs=window,
        name=f"stress-soak-{seed}",
    )
    q.ready()
    errors = []
    got = {
        (e, r): []
        for e in range(num_epochs)
        for r in range(num_trainers)
    }

    def producer():
        rng = random.Random(f"{seed}-producer")
        try:
            for epoch in range(num_epochs):
                q.new_epoch(epoch)  # blocks on the window
                for rank in range(num_trainers):
                    items = [
                        (epoch, rank, i) for i in range(items_per_rank)
                    ]
                    # Mix batched and single puts so actor-side
                    # put_nowait_batch and awaited put interleave.
                    split = rng.randrange(items_per_rank)
                    q.put_batch(rank, epoch, items[:split])
                    for it in items[split:]:
                        q.put(rank, epoch, it)
                    if rng.random() < 0.5:
                        time.sleep(rng.random() * 0.02)
                    q.producer_done(rank, epoch)
        except Exception as exc:  # noqa: BLE001 — surfaced by the test body
            errors.append(("producer", exc))

    def consumer(rank):
        rng = random.Random(f"{seed}-consumer-{rank}")
        try:
            for epoch in range(num_epochs):
                while True:
                    item = q.get(rank, epoch, timeout=DEADLINE_S)
                    if item is None:
                        q.task_done(rank, epoch)
                        break
                    got[(epoch, rank)].append(item)
                    if rng.random() < 0.3:
                        time.sleep(rng.random() * 0.01)
                    q.task_done(rank, epoch)
        except Exception as exc:  # noqa: BLE001
            errors.append((f"consumer{rank}", exc))

    threads = [
        threading.Thread(target=producer, name="producer", daemon=True)
    ] + [
        threading.Thread(
            target=consumer, args=(r,), name=f"consumer{r}", daemon=True
        )
        for r in range(num_trainers)
    ]
    _run_threads(threads)
    assert not errors, errors
    q.wait_until_all_epochs_done()
    for epoch in range(num_epochs):
        for rank in range(num_trainers):
            expect = [(epoch, rank, i) for i in range(items_per_rank)]
            assert got[(epoch, rank)] == expect, (
                f"epoch {epoch} rank {rank}: delivery not exactly-once/FIFO"
            )
    q.shutdown(force=True, grace_period_s=1)


@pytest.mark.parametrize("seed", _SEEDS[: max(2, _N_SEEDS // 2)])
def test_queue_consumer_dies_replacement_drains(local_runtime, seed):
    """A consumer stops acking mid-epoch (simulated death); the epoch
    window must block the producer's NEXT new_epoch until a replacement
    drains and acks the dead consumer's remaining items — then the trial
    completes. Exercises the confirm-then-recover interleaving the
    cluster failover path depends on."""
    rng = random.Random(seed)
    num_epochs = 2
    items_per_epoch = 10
    die_after = rng.randrange(1, items_per_epoch - 1)
    q = BatchQueue(
        num_epochs=num_epochs,
        num_trainers=1,
        max_concurrent_epochs=1,
        name=f"stress-die-{seed}",
    )
    q.ready()
    errors = []
    admitted = threading.Event()  # epoch 1 admitted by the window
    consumed = {0: [], 1: []}

    def producer():
        try:
            for epoch in range(num_epochs):
                q.new_epoch(epoch)
                if epoch == 1:
                    admitted.set()
                for i in range(items_per_epoch):
                    q.put(0, epoch, (epoch, i))
                q.producer_done(0, epoch)
        except Exception as exc:  # noqa: BLE001
            errors.append(("producer", exc))

    def dying_consumer():
        try:
            for _ in range(die_after):
                item = q.get(0, 0, timeout=DEADLINE_S)
                consumed[0].append(item)
                q.task_done(0, 0)
            # dies here: items remain unacked in (epoch 0, rank 0)
        except Exception as exc:  # noqa: BLE001
            errors.append(("dying", exc))

    def replacement():
        try:
            # Takes over epoch 0 after the original died, then runs
            # epoch 1 normally.
            for epoch in range(num_epochs):
                while True:
                    item = q.get(0, epoch, timeout=DEADLINE_S)
                    if item is None:
                        q.task_done(0, epoch)
                        break
                    consumed[epoch].append(item)
                    q.task_done(0, epoch)
        except Exception as exc:  # noqa: BLE001
            errors.append(("replacement", exc))

    # Daemon threads: a wedged thread must fail THIS test, not hang the
    # whole pytest process at exit.
    prod = threading.Thread(target=producer, name="producer", daemon=True)
    dyer = threading.Thread(target=dying_consumer, name="dying", daemon=True)
    prod.start()
    dyer.start()
    dyer.join(DEADLINE_S)
    assert not dyer.is_alive()
    # Window must hold epoch 1 closed while epoch 0 has unacked items.
    assert not admitted.wait(timeout=0.5), (
        "epoch window admitted epoch 1 while epoch 0 had unacked items"
    )
    repl = threading.Thread(target=replacement, name="replacement", daemon=True)
    repl.start()
    _join_threads([prod, repl])
    assert not errors, errors
    assert admitted.is_set()
    for epoch in range(num_epochs):
        assert sorted(consumed[epoch]) == [
            (epoch, i) for i in range(items_per_epoch)
        ], f"epoch {epoch} not exactly-once after consumer replacement"
    q.shutdown(force=True, grace_period_s=1)


@pytest.mark.parametrize("seed", _SEEDS)
def test_shuffle_delivery_soak_jittery_consumer(local_runtime, seed, tmp_path):
    """End-to-end soak: the real shuffle engine feeding a ShufflingDataset
    consumer whose iteration jitters (random sleeps), across 6 epochs with
    a 2-epoch window at tiny scale. Exercises the delivery/free-input
    threads against the window repeatedly; asserts exactly-once keys per
    epoch."""
    import numpy as np

    from ray_shuffling_data_loader_tpu.data_generation import generate_data
    from ray_shuffling_data_loader_tpu.dataset import ShufflingDataset

    rng = random.Random(seed)
    num_rows = 2000
    filenames, _ = generate_data(
        num_rows, 4, 1, 0.0, str(tmp_path / "soak-data")
    )
    ds = ShufflingDataset(
        filenames,
        num_epochs=6,
        num_trainers=1,
        batch_size=300,
        rank=0,
        num_reducers=3,
        max_concurrent_epochs=2,
        queue_name=f"stress-shuffle-{seed}",
        seed=seed,
    )
    for epoch in range(6):
        ds.set_epoch(epoch)
        keys = []
        for batch in ds:
            keys.append(np.asarray(batch["key"]))
            if rng.random() < 0.4:
                time.sleep(rng.random() * 0.05)
        keys = np.concatenate(keys)
        assert np.array_equal(np.sort(keys), np.arange(num_rows)), (
            f"epoch {epoch}: lost/duplicated rows under consumer jitter"
        )
