"""Durable run-ledger tests (ISSUE 16): gate + path resolution,
append/read roundtrip (torn lines skipped), record building with the
telemetry-derived sections present exactly when their planes are armed,
the ``tools/run_ledger.py`` CLI (list/show/diff and the ``--regress``
CI gate, both directions, on the checked-in fixtures), the shuffle()
integration (one record per run, plan + shape stamped), and the
zero-overhead-off contract (a fresh interpreter running a shuffle with
the gate unset never imports the plane and writes no file)."""

import json
import os
import subprocess
import sys
import threading

import pytest

from ray_shuffling_data_loader_tpu.telemetry import runledger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "run_ledger")
_CLI = os.path.join(_REPO, "tools", "run_ledger.py")


def _cli(*argv):
    return subprocess.run(
        [sys.executable, _CLI, *argv],
        capture_output=True, text=True, timeout=60, cwd=_REPO,
        env={**os.environ, "PYTHONPATH": _REPO},
    )


# ---------------------------------------------------------------------------
# Gate + path resolution
# ---------------------------------------------------------------------------


def test_gate_and_path_resolution(monkeypatch, tmp_path):
    for off in (None, "", "0", "off", "false", "no", "OFF"):
        if off is None:
            monkeypatch.delenv("RSDL_RUN_LEDGER", raising=False)
        else:
            monkeypatch.setenv("RSDL_RUN_LEDGER", off)
        assert not runledger.enabled()
        assert runledger.ledger_path() is None
    # Auto values resolve under the runtime dir (session-scoped).
    monkeypatch.setenv("RSDL_RUN_LEDGER", "auto")
    monkeypatch.setenv("RSDL_RUNTIME_DIR", str(tmp_path / "rt"))
    assert runledger.enabled()
    assert runledger.ledger_path() == str(
        tmp_path / "rt" / "runs" / "ledger.ndjson"
    )
    monkeypatch.delenv("RSDL_RUNTIME_DIR")
    assert runledger.ledger_path() == os.path.join(
        ".", "runs", "ledger.ndjson"
    )
    # Anything else is the explicit, durable path.
    explicit = tmp_path / "durable.ndjson"
    monkeypatch.setenv("RSDL_RUN_LEDGER", str(explicit))
    assert runledger.enabled()
    assert runledger.ledger_path() == str(explicit)


def test_record_run_off_is_noop(monkeypatch, tmp_path):
    monkeypatch.delenv("RSDL_RUN_LEDGER", raising=False)
    monkeypatch.setenv("RSDL_RUNTIME_DIR", str(tmp_path))
    assert runledger.record_run("done") is None
    assert not (tmp_path / "runs").exists()


# ---------------------------------------------------------------------------
# Append/read roundtrip
# ---------------------------------------------------------------------------


def test_append_read_roundtrip(monkeypatch, tmp_path):
    path = tmp_path / "runs" / "ledger.ndjson"  # parent auto-created
    monkeypatch.setenv("RSDL_RUN_LEDGER", str(path))
    rid1 = runledger.append_record({"id": "run-aaa-1", "status": "done"})
    rid2 = runledger.record_run("failed", error="boom", kind="bench")
    assert rid1 == "run-aaa-1" and rid2
    # A torn trailing line (crash mid-write) must not poison the read.
    with open(path, "a") as f:
        f.write('{"id": "run-torn')
    records = runledger.read(str(path))
    assert [r["id"] for r in records] == [rid1, rid2]
    assert records[1]["status"] == "failed"
    assert records[1]["error"] == "boom"
    assert records[1]["kind"] == "bench"
    assert records[1]["knobs"]["RSDL_RUN_LEDGER"] == str(path)


def test_concurrent_appends_interleave_whole_lines(monkeypatch, tmp_path):
    path = tmp_path / "ledger.ndjson"
    monkeypatch.setenv("RSDL_RUN_LEDGER", str(path))
    payload = {"blob": "x" * 4096}

    def spam(tag):
        for i in range(20):
            runledger.append_record(
                {"id": f"run-{tag}-{i}", "status": "done", **payload}
            )

    threads = [
        threading.Thread(target=spam, args=(t,)) for t in ("a", "b", "c")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    records = runledger.read(str(path))
    assert len(records) == 60  # no torn/interleaved lines lost
    assert len({r["id"] for r in records}) == 60


# ---------------------------------------------------------------------------
# Record building
# ---------------------------------------------------------------------------


def test_build_record_harvests_armed_planes(monkeypatch, tmp_path):
    from ray_shuffling_data_loader_tpu.telemetry import events, metrics, slo

    monkeypatch.setenv("RSDL_RUN_LEDGER", str(tmp_path / "l.ndjson"))
    monkeypatch.setenv("RSDL_METRICS", "1")
    monkeypatch.setenv("RSDL_METRICS_DIR", str(tmp_path / "spool"))
    metrics.refresh_from_env()
    metrics.reset()
    events.reset()
    slo.reset()
    try:
        metrics.registry.counter(
            "service.delivered_bytes", job="j-1"
        ).inc(1000)
        metrics.registry.counter("stall_seconds", cause="upstream").inc(2.5)
        metrics.registry.counter("stall_seconds", cause="staging").inc(1.5)
        rec = runledger.build_record(
            "done",
            duration_s=10.0,
            plan_label="rowwise",
            job_id="j-1",
            audit_verdicts=[{"epoch": 0, "ok": True}],
            extra={"bench": {"metric": "tp"}},
        )
        assert rec["status"] == "done" and rec["kind"] == "shuffle"
        assert rec["id"].startswith("run-")
        assert rec["pid"] == os.getpid()
        assert rec["duration_s"] == 10.0
        assert rec["plan"] == "rowwise"
        assert rec["job"] == {"id": "j-1", "name": None}
        assert rec["throughput"]["delivered_bytes"] == 1000
        assert rec["throughput"]["bytes_per_s"] == 100.0
        assert rec["stall_by_cause"] == {"staging": 1.5, "upstream": 2.5}
        assert rec["audit"] == {
            "ok": True, "verdicts": [{"epoch": 0, "ok": True}],
        }
        assert rec["bench"] == {"metric": "tp"}  # extra merged top-level
        assert rec["knobs"]["RSDL_METRICS"] == "1"
        assert "alerts_fired" not in rec  # nothing fired
        # One failing verdict folds the audit section to ok=False.
        rec = runledger.build_record(
            "done", audit_verdicts=[{"ok": True}, {"ok": False}],
        )
        assert rec["audit"]["ok"] is False
    finally:
        metrics.reset()
        events.reset()
        slo.reset()
        monkeypatch.undo()
        metrics.refresh_from_env()


def test_build_record_dark_planes_degrade(monkeypatch, tmp_path):
    """Metrics off: the record still carries identity + outcome, with
    every telemetry-derived section absent rather than empty."""
    from ray_shuffling_data_loader_tpu.telemetry import metrics

    monkeypatch.setenv("RSDL_RUN_LEDGER", str(tmp_path / "l.ndjson"))
    monkeypatch.delenv("RSDL_METRICS", raising=False)
    metrics.refresh_from_env()
    try:
        rec = runledger.build_record("failed", error="x" * 500)
        assert rec["status"] == "failed"
        assert len(rec["error"]) == 300  # clipped
        for section in ("throughput", "stall_by_cause", "epochs",
                        "critical", "capacity", "alerts_fired", "audit"):
            assert section not in rec, section
    finally:
        monkeypatch.undo()
        metrics.refresh_from_env()


# ---------------------------------------------------------------------------
# CLI: list / show / diff / --regress (both directions)
# ---------------------------------------------------------------------------


def test_cli_list_and_show():
    clean = os.path.join(_FIXTURES, "clean.ndjson")
    out = _cli("--ledger", clean, "list")
    assert out.returncode == 0, out.stderr
    assert "run-18f2a3b4c00-4242" in out.stdout
    assert out.stdout.strip().count("\n") == 1  # two records, one line each
    out = _cli("--ledger", clean, "show", "run-18f2a4")  # unique prefix
    assert out.returncode == 0, out.stderr
    rec = json.loads(out.stdout)
    assert rec["id"] == "run-18f2a4c5d00-4243"
    out = _cli("--ledger", clean, "show", "-1")  # newest by index
    assert json.loads(out.stdout)["id"] == "run-18f2a4c5d00-4243"
    out = _cli("--ledger", clean, "show", "run-nope")
    assert out.returncode == 3


def test_cli_diff_names_changed_fields():
    regressed = os.path.join(_FIXTURES, "regressed.ndjson")
    out = _cli("--ledger", regressed, "diff", "0", "1")
    assert out.returncode == 0, out.stderr
    assert "throughput" in out.stdout
    assert "stall[spill]" in out.stdout
    assert "knob RSDL_STORE_CAPACITY_FRACTION" in out.stdout


def test_cli_regress_gate_both_ways(tmp_path):
    clean = os.path.join(_FIXTURES, "clean.ndjson")
    regressed = os.path.join(_FIXTURES, "regressed.ndjson")
    out = _cli("--ledger", clean, "--regress", "0..1")
    assert out.returncode == 0, out.stdout + out.stderr
    out = _cli("--ledger", regressed, "--regress", "0..1")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "REGRESSION" in out.stdout
    assert "throughput dropped" in out.stdout
    assert "stall seconds rose" in out.stdout
    # Exit 3 ("nothing to compare") stays distinct from exit 1.
    out = _cli("--ledger", clean, "--regress", "0..run-nope")
    assert out.returncode == 3
    empty = tmp_path / "empty.ndjson"
    empty.write_text("")
    out = _cli("--ledger", str(empty), "--regress", "0..1")
    assert out.returncode == 3
    # A failed head over a done base is a regression by itself.
    failed = tmp_path / "failed.ndjson"
    with open(clean) as f:
        base_line = f.readline()
    head = json.loads(base_line)
    head.update(id="run-ffff-1", status="failed")
    head.pop("throughput", None)
    failed.write_text(base_line + json.dumps(head) + "\n")
    out = _cli("--ledger", str(failed), "--regress", "0..1")
    assert out.returncode == 1
    assert "head run failed" in out.stdout


# ---------------------------------------------------------------------------
# shuffle() integration: one record per run
# ---------------------------------------------------------------------------


def test_shuffle_run_appends_one_record(monkeypatch, tmp_path):
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import generate_file
    from ray_shuffling_data_loader_tpu.shuffle import (
        BatchConsumer,
        shuffle,
    )

    ledger = tmp_path / "ledger.ndjson"
    monkeypatch.setenv("RSDL_RUN_LEDGER", str(ledger))
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    files = [generate_file(0, 0, 256, 1, str(data_dir))[0]]

    class _Consumer(BatchConsumer):
        def consume(self, rank, epoch, batches):
            pass

        def producer_done(self, rank, epoch):
            pass

        def wait_until_ready(self, epoch):
            pass

        def wait_until_all_epochs_done(self):
            pass

    runtime.init(num_workers=1)
    try:
        shuffle(
            files, _Consumer(), num_epochs=1, num_reducers=2,
            num_trainers=1, seed=5,
        )
    finally:
        runtime.shutdown()
    records = runledger.read(str(ledger))
    assert len(records) == 1, records
    rec = records[0]
    assert rec["kind"] == "shuffle"
    assert rec["status"] == "done"
    assert rec["duration_s"] > 0
    assert rec["plan"]  # the resolved plan family is stamped
    assert rec["run"]["num_epochs"] == 1
    assert rec["run"]["num_reducers"] == 2
    assert rec["knobs"]["RSDL_RUN_LEDGER"] == str(ledger)


# ---------------------------------------------------------------------------
# Zero-overhead off
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_ledger_off_never_imports_plane(tmp_path):
    """RSDL_RUN_LEDGER unset: a fresh interpreter running a whole
    shuffle never loads the runledger module and creates no runs/
    directory anywhere under its cwd."""
    code = """
import os, sys
for k in list(os.environ):
    if k.startswith("RSDL_"):
        del os.environ[k]
os.environ["JAX_PLATFORMS"] = "cpu"
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_file
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle

class C(BatchConsumer):
    def consume(self, rank, epoch, batches): pass
    def producer_done(self, rank, epoch): pass
    def wait_until_ready(self, epoch): pass
    def wait_until_all_epochs_done(self): pass

files = [generate_file(0, 0, 128, 1, os.getcwd())[0]]
runtime.init(num_workers=1)
shuffle(files, C(), num_epochs=1, num_reducers=1, num_trainers=1, seed=1)
runtime.shutdown()
assert (
    "ray_shuffling_data_loader_tpu.telemetry.runledger" not in sys.modules
), "run ledger imported on a ledger-off run"
assert not os.path.exists("runs"), "ledger file created while off"
print("LEDGER_ZERO_OVERHEAD_OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": _REPO},
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr
    assert "LEDGER_ZERO_OVERHEAD_OK" in out.stdout
