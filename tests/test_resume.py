"""Durable epoch-state plane tests (ISSUE 13): the write-ahead journal
as a unit, graceful suspend (programmatic and SIGTERM), kill-and-resume
with bit-identical delivered digests, the degraded path when the store
segments are gone, the zero-overhead-off contract, and the
tools/replay.py time-travel check.

Recipe notes (PR 3): tests that arm env-gated planes run against a
FUNCTION-scoped runtime so the worker pool inherits the env. The
driver-kill legs spawn whole child drivers (their own runtimes, their
own shm dir) and SIGKILL/SIGTERM them mid-epoch-window — the pytest
process owns no runtime there, it only folds the journals, spools, and
digests the children leave behind.
"""

import collections
import json
import os
import subprocess
import sys

import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_file
from ray_shuffling_data_loader_tpu.runtime import faults
from ray_shuffling_data_loader_tpu.runtime import journal as jmod
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle
from ray_shuffling_data_loader_tpu.telemetry import audit as _audit
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NUM_FILES = 3
ROWS_PER_FILE = 300
TOTAL_ROWS = NUM_FILES * ROWS_PER_FILE
NUM_REDUCERS = 4
NUM_EPOCHS = 3
SEED = 7


# ---------------------------------------------------------------------------
# Journal unit tests (no runtime)
# ---------------------------------------------------------------------------


def _identity(**overrides):
    base = {
        "v": 1,
        "seed": SEED,
        "num_epochs": NUM_EPOCHS,
        "num_reducers": NUM_REDUCERS,
        "num_trainers": 1,
        "start_epoch": 0,
        "filenames": ["/data/a.parquet", "/data/b.parquet"],
        "narrow_to_32": False,
        "plan": "rowwise",
        "columns": None,
        "session": "sess-one",
        "faults": None,
        "faults_seed": None,
    }
    base.update(overrides)
    return base


def test_journal_fold_roundtrip(tmp_path, monkeypatch):
    """Append at every barrier kind, fold with load_run, and carry the
    fold into a successor journal — the successor's own fold must agree
    and the predecessor must be left superseded."""
    monkeypatch.setenv("RSDL_JOURNAL", str(tmp_path))
    identity = _identity()
    j = jmod.begin_run(identity)
    j.append("epoch", epoch=0, schedule="mapreduce")
    j.append(
        "map", epoch=0, file=0,
        refs=[{"id": "s-aa", "nbytes": 10, "session": "s"}] * NUM_REDUCERS,
    )
    j.append("map", epoch=0, file=1, counts=[1, 2, 3, 4])
    j.append(
        "reduce", epoch=0, reducer=0,
        refs=[{"id": "s-bb", "nbytes": 5, "session": "s"}],
    )
    j.append("deliver", epoch=0, reducer=0, rank=0, rows=220, sampled=3)
    j.append("deliver", epoch=0, reducer=1, rank=0, rows=230, sampled=5)
    j.append("epoch", epoch=1, schedule="mapreduce")
    j.append("deliver", epoch=1, reducer=0, rank=0, rows=200, sampled=0)
    j.append("verdict", epoch=0, ok=True, delivered_seq="abc123")
    jmod.end_run(j, status="failed")  # closed but resumable

    st = jmod.load_run(j.path)
    assert st.resumable() and not st.done and not st.suspended
    e0 = st.epochs[0]
    assert e0.schedule == "mapreduce"
    assert e0.maps[0]["refs"][0]["id"] == "s-aa"
    assert e0.maps[1]["counts"] == [1, 2, 3, 4]
    assert e0.reduces[0][0]["id"] == "s-bb"
    assert e0.delivered == 2  # cursor: reducers 0..1 delivered
    assert e0.rank_rows == {0: 450}
    assert e0.sampled == 5
    assert not e0.done
    assert st.epochs[1].delivered == 1
    assert st.verdicts[0]["delivered_seq"] == "abc123"

    # Ref JSON roundtrip preserves the store identity.
    ref = jmod.ref_from_json(e0.maps[0]["refs"][0])
    assert ref.object_id == "s-aa" and ref.nbytes == 10
    assert jmod.ref_to_json(ref)["id"] == "s-aa"

    # Carry forward into a successor; its self-contained fold agrees.
    j2 = jmod.begin_run(identity, resume=st)
    jmod.end_run(j2, status="failed")
    st2 = jmod.load_run(j2.path)
    assert st2.epochs[0].delivered == 2
    assert st2.epochs[0].rank_rows == {0: 450}
    assert st2.epochs[0].maps[1]["counts"] == [1, 2, 3, 4]
    assert st2.verdicts[0]["delivered_seq"] == "abc123"
    # The predecessor is superseded: discovery must find the successor.
    assert not jmod.load_run(j.path).resumable()
    found = jmod.find_resumable(str(tmp_path), identity)
    assert found is not None and found.run_id == j2.run_id

    # redeliver mode: the carry drops the delivery cursors of epochs
    # that were still in flight (a restarted consumer needs their full
    # streams again) but keeps completed stages.
    carried = list(st2.iter_records(carry_cursors=False))
    assert not any(r["kind"] == "deliver" for r in carried)
    assert any(r["kind"] == "map" for r in carried)


def test_journal_done_runs_are_not_resumable(tmp_path, monkeypatch):
    monkeypatch.setenv("RSDL_JOURNAL", str(tmp_path))
    identity = _identity()
    j = jmod.begin_run(identity)
    jmod.end_run(j)  # status="done"
    assert jmod.load_run(j.path).done
    assert jmod.find_resumable(str(tmp_path), identity) is None
    # An explicit path to a completed run refuses loudly.
    monkeypatch.setenv("RSDL_RESUME", "auto")
    state, _ = jmod.resolve_resume(None, identity)
    assert state is None
    with pytest.raises(ValueError, match="completed"):
        jmod.resolve_resume(j.path, identity)


def test_journal_torn_tail_and_header(tmp_path, monkeypatch):
    """Crash-mid-append debris never poisons the fold: a torn tail line
    is skipped, a headerless file raises instead of folding garbage."""
    monkeypatch.setenv("RSDL_JOURNAL", str(tmp_path))
    j = jmod.begin_run(_identity())
    j.append("deliver", epoch=0, reducer=0, rank=0, rows=100, sampled=0)
    jmod.end_run(j, status="failed")
    with open(j.path, "a") as f:
        f.write('{"kind": "deliver", "epoch": 0, "reducer": 1')  # torn
    st = jmod.load_run(j.path)
    assert st.epochs[0].delivered == 1  # the torn record did not fold

    bad = tmp_path / "run-headerless.ndjson"
    bad.write_text('{"kind": "deliver", "epoch": 0}\n')
    with pytest.raises(ValueError, match="identity"):
        jmod.load_run(str(bad))
    empty = tmp_path / "run-empty.ndjson"
    empty.write_text("")
    with pytest.raises(ValueError, match="empty or torn"):
        jmod.load_run(str(empty))


def test_identity_validation_refuses_stream_change():
    recorded = _identity()
    jmod.validate_identity(recorded, _identity())
    # Informational drift (fresh session, different fault schedule) is
    # exactly what a resume looks like — never a refusal.
    jmod.validate_identity(
        recorded,
        _identity(session="sess-two", faults="task.map:crash-entry:0.1"),
    )
    for key, val in (
        ("seed", 8),
        ("num_reducers", 8),
        ("plan", "block:2"),
        ("filenames", ["/data/other.parquet"]),
    ):
        with pytest.raises(ValueError, match=key):
            jmod.validate_identity(recorded, _identity(**{key: val}))


def test_resolve_resume_explicit_mismatch_raises(tmp_path, monkeypatch):
    """auto-discovery skips a non-matching journal silently (it is a
    different run, not an error); an EXPLICIT path must refuse."""
    monkeypatch.setenv("RSDL_JOURNAL", str(tmp_path))
    j = jmod.begin_run(_identity(seed=99))
    jmod.end_run(j, status="failed")
    state, mode = jmod.resolve_resume("auto", _identity())
    assert state is None and mode == "cursor"
    with pytest.raises(ValueError, match="seed"):
        jmod.resolve_resume(j.path, _identity())
    # Off spellings resolve to no resume at all.
    assert jmod.resolve_resume("off", _identity()) == (None, "cursor")
    assert jmod.resolve_resume(None, _identity()) == (None, "cursor")


def test_resume_from_auto_without_journal_runs_fresh(
    monkeypatch, resume_files, tmp_path
):
    """``shuffle(resume_from="auto")`` with ``RSDL_JOURNAL`` unset must
    start fresh and journal nothing — resolve_resume's "nothing to
    resume, nowhere to journal" outcome, not a begin_run crash."""
    monkeypatch.delenv("RSDL_JOURNAL", raising=False)
    monkeypatch.delenv("RSDL_RESUME", raising=False)
    runtime.init(num_workers=2)
    try:
        consumer = CollectingConsumer()
        shuffle(
            resume_files, consumer, num_epochs=1,
            num_reducers=NUM_REDUCERS, num_trainers=1, seed=5,
            resume_from="auto",
        )
        assert sorted(consumer.keys[(0, 0)]) == list(range(TOTAL_ROWS))
        assert not list(tmp_path.rglob("run-*.ndjson"))
    finally:
        runtime.shutdown()


# ---------------------------------------------------------------------------
# Zero-overhead off (fresh interpreter)
# ---------------------------------------------------------------------------

_ZERO_OVERHEAD_CHILD = """
import json, os, signal, sys
sys.path.insert(0, {repo!r})
os.environ.pop("RSDL_JOURNAL", None)
os.environ.pop("RSDL_RESUME", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_file
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle


class Drain(BatchConsumer):
    def consume(self, rank, epoch, batches):
        store = runtime.get_context().store
        for ref in batches:
            store.free(ref)

    def producer_done(self, rank, epoch):
        pass

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


fname, _ = generate_file(0, 0, 120, 1, os.environ["ZO_DATA_DIR"])
runtime.init(num_workers=1)
shuffle([fname], Drain(), num_epochs=1, num_reducers=2, num_trainers=1,
        seed=3)
print(json.dumps({{
    "journal_imported":
        "ray_shuffling_data_loader_tpu.runtime.journal" in sys.modules,
    "sigterm_is_default":
        signal.getsignal(signal.SIGTERM) == signal.SIG_DFL,
}}))
runtime.shutdown()
"""


def test_zero_overhead_off_fresh_interpreter(tmp_path):
    """The contract the whole plane hangs off: RSDL_JOURNAL unset means
    the journal module is never imported, no journal file is created,
    and no SIGTERM handler is installed — proven in a fresh interpreter
    (this pytest process imported the module long ago)."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("RSDL_")
    }
    env["ZO_DATA_DIR"] = str(data_dir)
    env["JAX_PLATFORMS"] = "cpu"
    env["RSDL_SHM_DIR"] = str(tmp_path / "shm")
    out = subprocess.run(
        [sys.executable, "-c", _ZERO_OVERHEAD_CHILD.format(repo=_REPO)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    report = json.loads(
        [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    )
    assert report["journal_imported"] is False
    assert report["sigterm_is_default"] is True
    # No journal artifacts anywhere near the run.
    assert not list(tmp_path.rglob("run-*.ndjson"))


# ---------------------------------------------------------------------------
# In-process suspend/resume (function-scoped runtime per the chaos recipe)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def resume_files(tmp_path_factory):
    """Parquet dataset written IN-PROCESS: the function-scoped runtimes
    below must spawn their pools after the env is armed, so nothing
    here may touch the runtime."""
    data_dir = tmp_path_factory.mktemp("resume-data")
    files = []
    for i in range(NUM_FILES):
        fname, _ = generate_file(
            i, i * ROWS_PER_FILE, ROWS_PER_FILE, 1, str(data_dir)
        )
        files.append(fname)
    return files


@pytest.fixture
def journal_env(monkeypatch, tmp_path):
    """Arm journal + strict audit + metrics, then a fresh runtime whose
    workers inherit all three. Function-scoped: suspend state and audit
    run boundaries are process-global, every test gets its own pool."""
    def arm(extra_env=None):
        spool = tmp_path / "audit-spool"
        spool.mkdir(exist_ok=True)
        monkeypatch.setenv("RSDL_JOURNAL", str(tmp_path / "journal"))
        monkeypatch.setenv("RSDL_AUDIT", "1")
        monkeypatch.setenv("RSDL_AUDIT_STRICT", "1")
        monkeypatch.setenv("RSDL_AUDIT_DIR", str(spool))
        monkeypatch.setenv("RSDL_METRICS", "1")
        monkeypatch.delenv("RSDL_RESUME", raising=False)
        # An ambient RSDL_FAULTS schedule (the CI resume lane's capped
        # chaos spec) deliberately rides along: recovery is exactly-once,
        # so injected crashes must be invisible to every assertion here.
        for k, v in (extra_env or {}).items():
            monkeypatch.setenv(k, v)
        _audit.refresh_from_env()
        _metrics.refresh_from_env()
        _metrics.registry.clear()
        faults.refresh_from_env()
        return runtime.init(num_workers=2)

    yield arm
    runtime.shutdown()
    jmod.clear_suspend()
    monkeypatch.undo()
    _audit.reset()
    _audit.refresh_from_env()
    _metrics.refresh_from_env()
    faults.refresh_from_env()


class CollectingConsumer(BatchConsumer):
    """Collects delivered keys per (epoch, rank); optionally requests a
    graceful suspend once one epoch's window has fully delivered."""

    def __init__(self, suspend_after_epoch=None):
        self.keys = collections.defaultdict(list)
        self.done = collections.defaultdict(bool)
        self.per_epoch = collections.Counter()
        self.suspend_after_epoch = suspend_after_epoch

    def consume(self, rank, epoch, batches, seq=None):
        store = runtime.get_context().store
        for ref in batches:
            cb = store.get_columns(ref)
            self.keys[(epoch, rank)].extend(cb["key"].tolist())
            store.free(ref)
        self.per_epoch[epoch] += 1
        if (
            self.suspend_after_epoch is not None
            and self.per_epoch[self.suspend_after_epoch] == NUM_REDUCERS
        ):
            self.suspend_after_epoch = None
            jmod.request_suspend()

    def producer_done(self, rank, epoch):
        self.done[(epoch, rank)] = True

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


def _journal_files(directory):
    return sorted(
        (
            os.path.join(directory, n)
            for n in os.listdir(directory)
            if n.startswith("run-") and n.endswith(".ndjson")
        ),
        key=os.path.getmtime,
    )


def test_suspend_resume_in_process(journal_env, resume_files, tmp_path):
    """Programmatic graceful suspend (the in-process twin of SIGTERM):
    shuffle() quiesces at the reducer barriers, journals the window,
    and raises RunSuspended. A second shuffle with resume_from="auto"
    skips the journaled-complete epoch outright (zero stage tasks),
    finishes the in-flight one from its cursor, and runs the
    never-admitted one fresh — combined streams exactly-once, strict
    audit reconciled across BOTH attempts."""
    journal_env()
    c1 = CollectingConsumer(suspend_after_epoch=0)
    with pytest.raises(jmod.RunSuspended) as excinfo:
        shuffle(
            resume_files, c1, num_epochs=NUM_EPOCHS,
            num_reducers=NUM_REDUCERS, num_trainers=1, seed=SEED,
        )
    journal_dir = os.environ["RSDL_JOURNAL"]
    assert os.path.dirname(excinfo.value.journal_path) == journal_dir
    st = jmod.load_run(excinfo.value.journal_path)
    assert st.suspended and st.resumable()
    assert st.epochs[0].done  # epoch 0's whole window was delivered
    assert c1.per_epoch[0] == NUM_REDUCERS
    # (The suspend request races the admission loop and the other
    # in-flight windows — which epochs got how far before quiescing is
    # deliberately unasserted; the exactly-once union below is the
    # invariant.)
    snap = _metrics.registry.snapshot()
    assert snap.get("recovery.suspended_runs") == 1.0

    c2 = CollectingConsumer()
    shuffle(
        resume_files, c2, num_epochs=NUM_EPOCHS,
        num_reducers=NUM_REDUCERS, num_trainers=1, seed=SEED,
        resume_from="auto",
    )
    # Journaled-complete epoch 0: skipped whole — the resumed run
    # re-delivered nothing for it and submitted zero stage tasks (the
    # new journal holds no fresh, non-carried stage records for it).
    assert c2.per_epoch[0] == 0
    new_journal = _journal_files(journal_dir)[-1]
    fresh_e0 = [
        rec
        for rec in map(json.loads, open(new_journal))
        if rec.get("kind") in ("map", "reduce")
        and rec.get("epoch") == 0
        and not rec.get("carried")
    ]
    assert fresh_e0 == []
    snap = _metrics.registry.snapshot()
    assert snap.get("recovery.resume_runs") == 1.0
    # Epoch 0 is deterministically skipped; epoch 1 may be too when its
    # window raced to completion before the suspend flag landed.
    assert snap.get("recovery.resume_epochs_skipped", 0) >= 1.0
    assert snap.get("recovery.resume_in_progress") == 0.0

    # Exactly-once across the suspension: per (epoch, rank) the two
    # attempts' streams are disjoint and their union is every row.
    for epoch in range(NUM_EPOCHS):
        combined = c1.keys[(epoch, 0)] + c2.keys[(epoch, 0)]
        assert sorted(combined) == list(range(TOTAL_ROWS)), (
            f"epoch {epoch} lost or duplicated rows across the suspend"
        )
        assert c2.done[(epoch, 0)]
    # Strict audit already reconciled inside shuffle(); assert the
    # verdicts fold both attempts into clean exactly-once epochs.
    summary = _audit.summary()
    assert summary["ok"] is True, summary
    # The resumed run completed: its journal is sealed, nothing left
    # to resume.
    assert not jmod.load_run(new_journal).resumable()
    assert jmod.find_resumable(journal_dir, st.identity) is None


# ---------------------------------------------------------------------------
# Kill-and-resume chaos legs (child drivers; SIGKILL / SIGTERM)
# ---------------------------------------------------------------------------

_CHILD_DRIVER = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["RESUME_REPO"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle
from ray_shuffling_data_loader_tpu.telemetry import audit as _audit
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

mode = os.environ["RESUME_MODE"]
files = json.loads(os.environ["RESUME_FILES"])
epochs = int(os.environ["RESUME_EPOCHS"])
reducers = int(os.environ["RESUME_REDUCERS"])

runtime.init(num_workers=2)

if mode == "victim":
    # Die mid-epoch-window, deterministically W.R.T. JOURNAL CONTENT:
    # a watcher thread folds the journal file until some epoch's whole
    # window is journaled delivered while another epoch's is partial,
    # then signals ourselves. (A parent-driven kill races the deliver
    # threads: by the time the parent reacts, the run may be done.)
    import glob as _glob
    import signal as _signal
    import threading as _threading

    _sig = getattr(_signal, "SIG" + os.environ.get("RESUME_KILL", "KILL"))
    _jdir = os.environ["RSDL_JOURNAL"]

    def _watch():
        while True:
            time.sleep(0.02)
            for path in _glob.glob(os.path.join(_jdir, "run-*.ndjson")):
                cursors = {}
                try:
                    with open(path) as f:
                        for line in f:
                            if not line.endswith("\n"):
                                break
                            try:
                                rec = json.loads(line)
                            except ValueError:
                                continue
                            if rec.get("kind") == "deliver":
                                e = int(rec["epoch"])
                                cursors[e] = max(
                                    cursors.get(e, 0),
                                    int(rec["reducer"]) + 1,
                                )
                except OSError:
                    continue
                full = any(c >= reducers for c in cursors.values())
                partial = any(0 < c < reducers for c in cursors.values())
                if full and partial:
                    os.kill(os.getpid(), _sig)
                    return

    _threading.Thread(target=_watch, daemon=True).start()


import threading as _thr

_epoch0_done = _thr.Event()
_epoch0_count = [0]


class Drain(BatchConsumer):
    def consume(self, rank, epoch, batches, seq=None):
        if mode == "victim" and epoch > 0:
            # Desynchronize the concurrent epoch windows: without this
            # they deliver in lockstep (their sleeps wake together) and
            # the "one window complete, another partial" state the
            # watcher kills on can collapse to milliseconds. Holding
            # later epochs until epoch 0's window fully delivered makes
            # that state hold for several deliveries' worth of time.
            _epoch0_done.wait(timeout=60)
        store = runtime.get_context().store
        for ref in batches:
            store.free(ref)
        print("DELIVERED %d %s" % (epoch, seq), flush=True)
        if mode == "victim":
            if epoch == 0:
                _epoch0_count[0] += 1
                if _epoch0_count[0] >= reducers:
                    _epoch0_done.set()
            time.sleep(0.1)  # widen the kill window

    def producer_done(self, rank, epoch):
        pass

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


shuffle(files, Drain(), num_epochs=epochs, num_reducers=reducers,
        num_trainers=1, seed=int(os.environ["RESUME_SEED"]))
verdicts = _audit.reconcile(range(epochs))
snap = _metrics.registry.snapshot() if _metrics.enabled() else {}
print("RESULT " + json.dumps({
    "verdicts": [{"epoch": v["epoch"], "ok": v["ok"],
                  "delivered_seq": v.get("delivered_seq")}
                 for v in verdicts],
    "recovery": {k: v for k, v in snap.items()
                 if k.startswith("recovery.")},
}), flush=True)
runtime.shutdown()
if os.environ.get("RESUME_CAPACITY"):
    from ray_shuffling_data_loader_tpu.telemetry import capacity
    print("CAPACITY " + json.dumps(capacity.ledger()["totals"]),
          flush=True)
"""


class _ResumeHarness:
    """Shared driver-process harness: a control run's digests plus the
    work dirs the victim/resume legs reuse."""

    def __init__(self, files, work):
        self.files = files
        self.work = work
        self.journal_dir = os.path.join(work, "journal")
        self.shm_dir = os.path.join(work, "shm")
        self.spool_run = os.path.join(work, "audit-run")
        self.metrics_dir = os.path.join(work, "metrics")
        spool_ctrl = os.path.join(work, "audit-ctrl")
        for d in (self.journal_dir, self.shm_dir, self.spool_run,
                  self.metrics_dir, spool_ctrl):
            os.makedirs(d)
        ctrl, _, lines, _rc = self.child(
            "control", {"RSDL_AUDIT_DIR": spool_ctrl,
                        "RSDL_SHM_DIR": os.path.join(work, "shm-ctrl")},
        )
        assert ctrl is not None, "\n".join(lines[-30:])
        self.control_seq = {
            v["epoch"]: v["delivered_seq"] for v in ctrl["verdicts"]
        }
        assert len(self.control_seq) == NUM_EPOCHS

    def base_env(self):
        env = {
            k: v
            for k, v in os.environ.items()
            if not k.startswith("RSDL_")
        }
        # The chaos schedule (when the CI resume lane arms one) rides
        # into every child driver: digest equality must hold across a
        # preemption even while fault recovery is churning underneath.
        for key in ("RSDL_FAULTS", "RSDL_FAULTS_SEED"):
            if os.environ.get(key):
                env[key] = os.environ[key]
        env.update(
            RESUME_REPO=_REPO,
            RESUME_FILES=json.dumps(self.files),
            RESUME_EPOCHS=str(NUM_EPOCHS),
            RESUME_REDUCERS=str(NUM_REDUCERS),
            RESUME_SEED=str(SEED),
            RSDL_SHM_DIR=self.shm_dir,
            RSDL_AUDIT="1",
            RSDL_METRICS="1",
            JAX_PLATFORMS="cpu",
        )
        return env

    def child(self, mode, extra):
        """Run one driver child to completion (victims kill themselves
        from a journal-watching thread once the kill condition — one
        epoch window journaled complete, another partial — holds)."""
        env = dict(self.base_env(), RESUME_MODE=mode, **extra)
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD_DRIVER],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True,
        )
        result, capacity, lines = None, None, []
        for line in proc.stdout:
            line = line.rstrip()
            lines.append(line)
            if line.startswith("RESULT "):
                result = json.loads(line[len("RESULT "):])
            elif line.startswith("CAPACITY "):
                capacity = json.loads(line[len("CAPACITY "):])
        returncode = proc.wait()
        return result, capacity, lines, returncode

    def victim(self, sig):
        _, _, lines, returncode = self.child(
            "victim",
            # The victim shares the resume leg's metrics spool so its
            # workers' capacity create-records (flushed at the task-done
            # barrier) survive the kill — the resumed run's ledger fold
            # then resolves the superseded session's deletes against
            # real creates instead of orphans.
            {"RSDL_AUDIT_DIR": self.spool_run,
             "RSDL_JOURNAL": self.journal_dir,
             "RSDL_METRICS_DIR": self.metrics_dir,
             "RESUME_KILL": sig},
        )
        files = _journal_files(self.journal_dir)
        assert files, "victim journaled nothing:\n" + "\n".join(lines[-20:])
        st = jmod.load_run(files[-1])
        assert st.resumable(), (
            "victim's journal is not resumable (kill condition never "
            "held?):\n" + "\n".join(lines[-20:])
        )
        return st, lines, returncode

    def resume(self):
        res, cap, lines, _rc = self.child(
            "resume",
            {"RSDL_AUDIT_DIR": self.spool_run,
             "RSDL_JOURNAL": self.journal_dir,
             "RSDL_RESUME": "auto", "RSDL_AUDIT_STRICT": "1",
             "RSDL_METRICS_DIR": self.metrics_dir,
             "RESUME_CAPACITY": "1"},
        )
        assert res is not None, (
            "resumed driver died:\n" + "\n".join(lines[-40:])
        )
        return res, cap


@pytest.fixture
def resume_harness(resume_files, tmp_path):
    return _ResumeHarness(resume_files, str(tmp_path))


def test_sigkill_and_resume_bit_identical(resume_harness):
    """THE acceptance scenario: the driver is SIGKILLed mid-epoch-window
    (no goodbye, no flush beyond the barriers already taken), a fresh
    driver resumes from the journal, and every epoch's order-sensitive
    per-rank delivered_seq digest is bit-identical to an uninterrupted
    same-seed run — under strict audit, with the journaled-complete
    epoch re-executing zero stage tasks and the capacity ledger's
    residency folding to zero after cleanup."""
    h = resume_harness
    old, _, _ = h.victim("KILL")
    res, cap = h.resume()

    res_seq = {v["epoch"]: v["delivered_seq"] for v in res["verdicts"]}
    assert res_seq == h.control_seq, (
        f"delivered_seq diverged: control={h.control_seq} resumed={res_seq}"
    )
    assert all(v["ok"] for v in res["verdicts"])
    rec = res["recovery"]
    assert rec.get("recovery.resume_runs") == 1.0
    assert rec.get("recovery.resumed_epochs", 0) >= 1.0
    # The fully-delivered epoch was skipped whole: zero map/reduce
    # tasks — counter-asserted, and its window never re-entered the
    # new journal as fresh stage records.
    assert rec.get("recovery.resume_epochs_skipped", 0) >= 1.0
    done_epochs = [
        e for e, st in old.epochs.items() if st.delivered >= NUM_REDUCERS
    ]
    assert done_epochs, "kill landed before any epoch window completed"
    new_journal = _journal_files(h.journal_dir)[-1]
    fresh = [
        r
        for r in map(json.loads, open(new_journal))
        if r.get("kind") in ("map", "reduce")
        and r.get("epoch") in done_epochs
        and not r.get("carried")
    ]
    assert fresh == []
    # Preempted-session segments were swept (the resumed run owns the
    # superseded session's reclamation) and the ledger agrees: nothing
    # resident on any tier once the run cleaned up.
    assert os.listdir(h.shm_dir) == []
    assert cap is not None
    for tier, cell in cap.items():
        assert cell["resident_bytes"] == 0, (tier, cap)


def test_sigterm_graceful_suspend_then_resume(resume_harness):
    """The preemption-notice path: SIGTERM makes the journal-armed
    driver quiesce its windows, flush, journal the suspension, and
    leave with exit 0 — and the resumed run completes the stream
    bit-identically."""
    h = resume_harness
    st, lines, returncode = h.victim("TERM")
    # The SIGTERM child must have exited through the graceful path:
    # exit 0 with an explicit suspension record journaled.
    assert returncode == 0, lines[-15:]
    assert st.suspended, lines[-10:]
    res, _ = h.resume()
    res_seq = {v["epoch"]: v["delivered_seq"] for v in res["verdicts"]}
    assert res_seq == h.control_seq
    assert all(v["ok"] for v in res["verdicts"])


def test_sigkill_resume_with_segments_dropped(resume_harness):
    """Degraded resume: every store segment of the preempted session is
    gone (host swapped out from under the job). Stage re-attach fails
    closed, everything journaled-but-undelivered re-executes from the
    seed, and the delivered digests STILL match the uninterrupted run."""
    h = resume_harness
    h.victim("KILL")
    for name in os.listdir(h.shm_dir):
        os.unlink(os.path.join(h.shm_dir, name))
    res, _ = h.resume()
    res_seq = {v["epoch"]: v["delivered_seq"] for v in res["verdicts"]}
    assert res_seq == h.control_seq, (
        f"delivered_seq diverged: control={h.control_seq} resumed={res_seq}"
    )
    assert all(v["ok"] for v in res["verdicts"])
    # The degraded path was actually taken: journaled stages whose
    # segments vanished were re-executed, not re-attached.
    rec = res["recovery"]
    reexecuted = sum(
        v for k, v in rec.items()
        if k.startswith("recovery.resume_reexecuted")
    )
    assert reexecuted > 0, rec


# ---------------------------------------------------------------------------
# tools/replay.py
# ---------------------------------------------------------------------------


def test_replay_reproduces_and_detects_divergence(
    journal_env, resume_files, tmp_path
):
    """A journaled, completed run replays bit-identically (exit 0); a
    journal whose recorded digest is tampered with makes the same
    replay exit 1 and name the diverging field."""
    journal_env()
    shuffle(
        resume_files, CollectingConsumer(), num_epochs=2,
        num_reducers=NUM_REDUCERS, num_trainers=1, seed=SEED,
    )
    journal_dir = os.environ["RSDL_JOURNAL"]
    journal_path = _journal_files(journal_dir)[-1]
    st = jmod.load_run(journal_path)
    assert st.done and sorted(st.verdicts) == [0, 1]

    env = {
        k: v for k, v in os.environ.items() if not k.startswith("RSDL_")
    }
    env["RSDL_SHM_DIR"] = str(tmp_path / "replay-shm")
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "replay.py"),
         journal_path, "--epoch", "1"],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    report = json.loads(out.stdout)
    assert report["ok"] is True
    assert report["epochs"]["1"]["ok"] is True
    assert report["epochs"]["1"]["diverged"] == {}

    # Tamper with the recorded digest: replay must refute it.
    lines = open(journal_path).read().splitlines()
    tampered = []
    for line in lines:
        rec = json.loads(line)
        if rec.get("kind") == "verdict" and rec.get("epoch") == 1:
            rec["delivered_seq"] = "0" * 16
            line = json.dumps(rec)
        tampered.append(line)
    with open(journal_path, "w") as f:
        f.write("\n".join(tampered) + "\n")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "replay.py"),
         journal_path, "--epoch", "1"],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    report = json.loads(out.stdout)
    assert report["ok"] is False
    assert "delivered_seq" in report["epochs"]["1"]["diverged"]
