"""Pod-launcher logic test (VERDICT r4 item 8): ``launch_tpu_pod.sh`` is
the analog of the reference's autoscaler flow (reference
``benchmarks/cluster.yaml`` + ``examples/horovod/cluster.yaml``) and can't
run against real pod hardware in CI — but its command-generation logic can:
``--print-only`` emits the exact gcloud sequence without executing it."""

import os
import subprocess

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks",
    "launch_tpu_pod.sh",
)


def _run(*args, workers="4", env_extra=None):
    env = dict(
        os.environ,
        TPU_NAME="my-v5e-16",
        ZONE="us-west4-a",
        PRINT_ONLY_WORKERS=workers,
        **(env_extra or {}),
    )
    return subprocess.run(
        ["bash", _SCRIPT, "--print-only", *args],
        capture_output=True,
        text=True,
        env=env,
        timeout=60,
    )


def test_print_only_emits_full_gcloud_sequence():
    proc = _run("--num-rows", "400000000", "--num-trainers", "16")
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.strip().splitlines()
    # head + describe + (workers-1) joins + benchmark
    assert len(lines) == 6, lines
    assert all(line.startswith("gcloud compute tpus tpu-vm") for line in lines)
    head, describe, j1, j2, j3, bench = lines
    # Head: worker 0 starts the cluster on the configured port.
    assert "--worker=0" in head
    assert "init_cluster(listen_port=43211)" in head
    # Worker discovery via describe.
    assert "describe" in describe and "networkEndpoints" in describe
    # Every non-head host joins with the head's address.
    for idx, join in ((1, j1), (2, j2), (3, j3)):
        assert f"--worker={idx}" in join
        assert "runtime.cluster join" in join.replace("\\", "")
        assert "HEAD_ADDRESS" in join
    # Benchmark runs on the head with the passthrough workload args.
    assert "--worker=0" in bench
    assert "benchmark.py" in bench
    # Whole-flag matches (shlex-unquoted): a bare "16" would also match
    # inside TPU_NAME="my-v5e-16" and prove nothing about passthrough.
    import shlex

    bench_plain = " ".join(shlex.split(bench))
    assert "--num-rows 400000000" in bench_plain
    assert "--num-trainers 16" in bench_plain
    # Nothing was actually executed: gcloud isn't even installed here.
    assert "head up at" not in proc.stdout


def test_print_only_worker_count_scales_joins():
    proc = _run(workers="8")
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.strip().splitlines()
    joins = [ln for ln in lines if "cluster" in ln and "join" in ln]
    assert len(joins) == 7


def test_missing_tpu_name_fails():
    env = dict(os.environ, ZONE="z")
    env.pop("TPU_NAME", None)
    proc = subprocess.run(
        ["bash", _SCRIPT, "--print-only"],
        capture_output=True,
        text=True,
        env=env,
        timeout=60,
    )
    assert proc.returncode != 0
    assert "TPU_NAME" in proc.stderr
