"""Batch-queue semantics tests.

Mirrors the reference's queue coverage (``tests/test_batch_queue.py:23-288``):
FIFO, blocking/non-blocking/timeout get/put, sync + async, batched ops,
size tracking, shutdown, concurrency, and end-to-end streaming consumption
with the producer-done sentinel."""

import asyncio
import threading
import time

import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.batch_queue import BatchQueue, Empty, Full


@pytest.fixture
def make_queue(local_runtime):
    queues = []

    def factory(**kwargs):
        kwargs.setdefault("num_epochs", 1)
        kwargs.setdefault("num_trainers", 1)
        kwargs.setdefault("max_concurrent_epochs", 1)
        q = BatchQueue(**kwargs)
        q.ready()
        queues.append(q)
        return q

    yield factory
    for q in queues:
        if q.actor is not None:
            q.shutdown(force=True, grace_period_s=1)


def test_simple_usage(make_queue):
    q = make_queue()
    items = list(range(10))
    for item in items:
        q.put(rank=0, epoch=0, item=item)
    for item in items:
        assert item == q.get(rank=0, epoch=0)


def test_get(make_queue):
    q = make_queue()
    q.put(rank=0, epoch=0, item=0)
    assert q.get(rank=0, epoch=0, block=False) == 0

    q.put(rank=0, epoch=0, item=1)
    assert q.get(rank=0, epoch=0, timeout=0.2) == 1

    with pytest.raises(ValueError):
        q.get(rank=0, epoch=0, timeout=-1)

    with pytest.raises(Empty):
        q.get_nowait(rank=0, epoch=0)

    with pytest.raises(Empty):
        q.get(rank=0, epoch=0, timeout=0.2)


def test_get_async(make_queue):
    q = make_queue()

    async def scenario():
        await q.put_async(rank=0, epoch=0, item=0)
        assert await q.get_async(rank=0, epoch=0, block=False) == 0

        await q.put_async(rank=0, epoch=0, item=1)
        assert await q.get_async(rank=0, epoch=0, timeout=0.2) == 1

        with pytest.raises(ValueError):
            await q.get_async(rank=0, epoch=0, timeout=-1)

        with pytest.raises(Empty):
            await q.get_async(rank=0, epoch=0, block=False)

        with pytest.raises(Empty):
            await q.get_async(rank=0, epoch=0, timeout=0.2)

    asyncio.run(scenario())


def test_put(make_queue):
    q = make_queue(maxsize=1)

    q.put(rank=0, epoch=0, item=0, block=False)
    assert q.get(rank=0, epoch=0) == 0

    q.put(rank=0, epoch=0, item=1, timeout=0.2)
    assert q.get(rank=0, epoch=0) == 1

    with pytest.raises(ValueError):
        q.put(rank=0, epoch=0, item=0, timeout=-1)

    q.put(rank=0, epoch=0, item=0)
    with pytest.raises(Full):
        q.put_nowait(rank=0, epoch=0, item=1)

    with pytest.raises(Full):
        q.put(rank=0, epoch=0, item=1, timeout=0.2)


def test_put_async(make_queue):
    q = make_queue(maxsize=1)

    async def scenario():
        await q.put_async(rank=0, epoch=0, item=0, block=False)
        assert await q.get_async(rank=0, epoch=0) == 0

        await q.put_async(rank=0, epoch=0, item=1, timeout=0.2)
        assert await q.get_async(rank=0, epoch=0) == 1

        with pytest.raises(ValueError):
            await q.put_async(rank=0, epoch=0, item=0, timeout=-1)

        await q.put_async(rank=0, epoch=0, item=0)
        with pytest.raises(Full):
            await q.put_async(rank=0, epoch=0, item=1, block=False)

        with pytest.raises(Full):
            await q.put_async(rank=0, epoch=0, item=1, timeout=0.2)

    asyncio.run(scenario())


def test_concurrent_get(make_queue):
    # A blocked get in another thread is fulfilled by a later put
    # (reference uses a remote task, ``test_batch_queue.py:131-142``).
    q = make_queue()
    result = {}

    def getter():
        result["value"] = q.get(rank=0, epoch=0)

    t = threading.Thread(target=getter)
    t.start()
    with pytest.raises(Empty):
        q.get_nowait(rank=0, epoch=0)
    time.sleep(0.1)
    assert t.is_alive()  # still blocked
    q.put(rank=0, epoch=0, item=1)
    t.join(timeout=5)
    assert result["value"] == 1


def test_concurrent_put(make_queue):
    q = make_queue(maxsize=1)
    q.put(rank=0, epoch=0, item=1)

    t = threading.Thread(target=lambda: q.put(rank=0, epoch=0, item=2))
    t.start()
    with pytest.raises(Full):
        q.put_nowait(rank=0, epoch=0, item=3)
    time.sleep(0.1)
    assert t.is_alive()  # blocked on full queue
    assert q.get(rank=0, epoch=0) == 1
    t.join(timeout=5)
    assert q.get(rank=0, epoch=0) == 2


def test_put_batch_all_or_nothing(make_queue):
    """A timed-out batched put must leave the queue untouched — no partial
    enqueue (regression: items put before the timeout used to land)."""
    q = make_queue(maxsize=2)
    q.put(rank=0, epoch=0, item="resident")
    with pytest.raises(Full):
        q.put_batch(rank=0, epoch=0, items=["a", "b"], timeout=0.2)
    # Nothing from the failed batch landed.
    assert q.qsize(rank=0, epoch=0) == 1
    assert q.get(rank=0, epoch=0) == "resident"
    # With room, the same batch goes through atomically.
    q.put_batch(rank=0, epoch=0, items=["a", "b"], timeout=0.2)
    assert q.get(rank=0, epoch=0) == "a"
    assert q.get(rank=0, epoch=0) == "b"
    # A batch larger than maxsize can never fit: immediate Full.
    with pytest.raises(Full):
        q.put_batch(rank=0, epoch=0, items=["a", "b", "c"], timeout=0.2)


def test_batch(make_queue):
    q = make_queue(maxsize=1)

    with pytest.raises(Full):
        q.put_nowait_batch(rank=0, epoch=0, items=[1, 2])

    with pytest.raises(Empty):
        q.get_nowait_batch(rank=0, epoch=0, num_items=1)

    big_q = make_queue(maxsize=100)
    big_q.put_nowait_batch(rank=0, epoch=0, items=list(range(100)))
    assert big_q.get_nowait_batch(rank=0, epoch=0, num_items=100) == list(
        range(100)
    )


def test_qsize(make_queue):
    q = make_queue()
    items = list(range(10))
    size = 0
    assert q.qsize(rank=0, epoch=0) == size
    for item in items:
        q.put(rank=0, epoch=0, item=item)
        size += 1
        assert q.qsize(rank=0, epoch=0) == size
    for item in items:
        assert q.get(rank=0, epoch=0) == item
        size -= 1
        assert q.qsize(rank=0, epoch=0) == size
    assert len(q) == 0


def test_shutdown(make_queue):
    q = make_queue()
    actor = q.actor
    q.shutdown()
    assert q.actor is None
    with pytest.raises(runtime.ActorDiedError):
        actor.call("empty", 0, 0)


def test_epoch_window_backpressure(make_queue):
    # new_epoch blocks until the oldest epoch's producers are done AND all
    # its items are task_done-acked (reference ``batch_queue.py:395-418``).
    q = make_queue(num_epochs=3, num_trainers=1, max_concurrent_epochs=1)
    q.new_epoch(0)
    q.put(rank=0, epoch=0, item="a")
    q.producer_done(rank=0, epoch=0)

    admitted = threading.Event()

    def admit_next():
        q.new_epoch(1)
        admitted.set()

    t = threading.Thread(target=admit_next)
    t.start()
    time.sleep(0.3)
    assert not admitted.is_set()  # epoch 0 not drained yet

    assert q.get(rank=0, epoch=0) == "a"
    assert q.get(rank=0, epoch=0) is None  # producer-done sentinel
    q.task_done(rank=0, epoch=0, num_items=2)
    t.join(timeout=5)
    assert admitted.is_set()


def test_producer_done_sentinel_via_get_batch(make_queue):
    q = make_queue()
    q.put_batch(rank=0, epoch=0, items=["x", "y"])
    q.producer_done(rank=0, epoch=0)
    time.sleep(0.1)
    batch = q.get_batch(rank=0, epoch=0)
    assert batch == ["x", "y", None]


def test_connect_by_name(make_queue):
    q = make_queue(name="bq-test-connect")
    q.put(rank=0, epoch=0, item=42)
    q2 = BatchQueue(
        num_epochs=1,
        num_trainers=1,
        max_concurrent_epochs=1,
        name="bq-test-connect",
        connect=True,
    )
    assert q2.get(rank=0, epoch=0) == 42


def test_pull_from_streaming_batch_queue(local_runtime, make_queue):
    """End-to-end streaming consumption across epochs with refs through the
    store (miniature of ``ShufflingDataset.__iter__``; reference
    ``test_batch_queue.py:231-288``)."""
    import numpy as np

    store = local_runtime.store
    num_epochs = 5
    batch_size = 4
    q = make_queue(
        num_epochs=num_epochs, num_trainers=1, max_concurrent_epochs=num_epochs
    )
    consumed = []
    done = threading.Event()

    def consume():
        for epoch in range(num_epochs):
            is_done = False
            while not is_done:
                for item in q.get_batch(rank=0, epoch=epoch):
                    if item is None:
                        is_done = True
                    else:
                        consumed.extend(
                            store.get_columns(item)["v"].tolist()
                        )
                        time.sleep(0.05)
        done.set()

    t = threading.Thread(target=consume)
    t.start()
    data = list(range(batch_size * num_epochs))
    for epoch, idx in enumerate(range(0, len(data), batch_size)):
        refs = [
            store.put_columns({"v": np.array([item])})
            for item in data[idx : idx + batch_size]
        ]
        q.put_nowait_batch(rank=0, epoch=epoch, items=refs)
        q.put_nowait(rank=0, epoch=epoch, item=None)
    assert done.wait(timeout=30)
    t.join()
    assert sorted(consumed) == data


def test_put_batch_small_maxsize_stress(make_queue):
    """Event-driven producer wakeups under contention (regression for the
    5 ms poll loop): several threads race timed put_batch calls into a
    maxsize-2 queue against a slow consumer. Every batch must land intact
    (all-or-nothing) with no lost wakeup — a missed set() would surface
    here as a Full timeout despite the consumer draining."""
    q = make_queue(maxsize=2)
    n_producers = 4
    batches_per_producer = 25
    errors = []

    def producer(pid):
        try:
            for i in range(batches_per_producer):
                q.put_batch(
                    rank=0, epoch=0, items=[(pid, i, 0), (pid, i, 1)],
                    timeout=30,
                )
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [
        threading.Thread(target=producer, args=(p,))
        for p in range(n_producers)
    ]
    for t in threads:
        t.start()
    total = n_producers * batches_per_producer * 2
    got = [q.get(rank=0, epoch=0, timeout=30) for _ in range(total)]
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    assert not errors
    assert len(got) == total
    # Atomicity: the two items of any batch are adjacent in FIFO order
    # (the enqueue loop never awaits between put_nowait calls).
    for a, b in zip(got[::2], got[1::2]):
        assert a[:2] == b[:2] and (a[2], b[2]) == (0, 1)
