"""Real-TPU validation of the resident loader (gated: RSDL_TPU_TESTS=1).

``tests/conftest.py`` pins the test process to CPU, so the check runs in a
fresh subprocess allowed to bring up the accelerator. Proves on hardware
what the CPU-mesh tests prove functionally: exactly-once delivery from an
HBM-resident buffer, stream equality of the materialized-epoch and
per-batch-gather schedules, and (printed, not asserted) their relative
epoch timings — the numbers that decide the default schedule on TPU.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("RSDL_TPU_TESTS") != "1",
    reason="set RSDL_TPU_TESTS=1 on a TPU host to run real-chip tests",
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TPU_SCRIPT = r"""
import os, sys, time
sys.path.insert(0, os.environ["RSDL_TEST_REPO"])
import numpy as np
import jax

assert jax.default_backend() == "tpu", jax.default_backend()

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import (
    LABEL_COLUMN, EMBEDDING_COLUMNS, generate_data,
)
from ray_shuffling_data_loader_tpu.resident import (
    DeviceResidentShufflingDataset,
)

runtime.init(num_workers=2)
data_dir = os.environ["RSDL_TEST_TMP"]
filenames, _ = generate_data(200_000, 4, 1, 0.0, data_dir)
features = EMBEDDING_COLUMNS[:6] + ["key"]

streams = {}
for mat in (True, False):
    ds = DeviceResidentShufflingDataset(
        filenames,
        num_epochs=2,
        batch_size=25_000,
        feature_columns=features,
        label_column=LABEL_COLUMN,
        seed=5,
        materialize_epoch=mat,
    )
    epochs = []
    for epoch in range(2):
        t0 = time.perf_counter()
        ds.set_epoch(epoch)
        keys = np.concatenate(
            [np.asarray(f["key"]) for f, _ in ds]
        )
        jax.effects_barrier()
        dt = time.perf_counter() - t0
        assert np.array_equal(np.sort(keys), np.arange(200_000)), (
            mat, epoch,
        )
        epochs.append(keys)
        print(f"RESIDENT_TPU mat={mat} epoch={epoch} {dt:.3f}s", flush=True)
    streams[mat] = epochs
for epoch in range(2):
    assert np.array_equal(streams[True][epoch], streams[False][epoch])

# Epoch-fused training compiled on the real chip: one lax.scan per
# epoch over the resident buffer, loss curve bit-comparable to the
# per-batch step on the same data (the path the round-end bench takes).
import jax.numpy as jnp
from ray_shuffling_data_loader_tpu.resident import make_fused_epoch

ds_f = DeviceResidentShufflingDataset(
    filenames,
    num_epochs=2,
    batch_size=25_000,
    feature_columns=features,
    label_column=LABEL_COLUMN,
    seed=5,
)

def step_body(state, feats, label):
    def loss_fn(w):
        pred = w * feats["key"].astype(jnp.float32) / 200_000.0
        return jnp.mean((pred - label) ** 2)
    loss, g = jax.value_and_grad(loss_fn)(state)
    return state - 0.05 * g, {"loss": loss}

run = make_fused_epoch(ds_f, step_body, donate_state=False)
state_f = jnp.float32(0.5)
t0 = time.perf_counter()
for epoch in range(2):
    state_f, losses = run(state_f, epoch)
    jax.block_until_ready(losses)
print(f"RESIDENT_TPU fused 2 epochs {time.perf_counter()-t0:.3f}s", flush=True)

ds_p = DeviceResidentShufflingDataset(
    filenames,
    num_epochs=2,
    batch_size=25_000,
    feature_columns=features,
    label_column=LABEL_COLUMN,
    seed=5,
)
step = jax.jit(step_body)
state_p = jnp.float32(0.5)
for epoch in range(2):
    ds_p.set_epoch(epoch)
    for feats, label in ds_p:
        state_p, _ = step(state_p, feats, label)
assert abs(float(state_f) - float(state_p)) < 1e-5, (
    float(state_f), float(state_p),
)
print("RESIDENT_TPU_FUSED_OK", flush=True)
runtime.shutdown()
print("RESIDENT_TPU_OK", flush=True)
"""


def test_resident_loader_on_tpu(tmp_path):
    env = dict(os.environ, RSDL_TEST_REPO=_REPO, RSDL_TEST_TMP=str(tmp_path))
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-u", "-c", _TPU_SCRIPT],
        capture_output=True,
        text=True,
        timeout=900,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "RESIDENT_TPU_OK" in proc.stdout, proc.stdout[-2000:]
