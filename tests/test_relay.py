"""Cross-host telemetry federation tests (ISSUE 19).

Fast tier: the relay's pure mechanics — receiver restamping of relayed
metrics snapshots (clock-skew safety both directions), sink append
idempotency (CRC, gaps, overlap trim after reconnect), the shared-
filesystem skip handshake, cursor resume, bounded buffering
(drop-ahead to a line boundary), and a full shipper→sink round trip
over the real authed TCP transport inside one process (both halves take
an explicit spool-dir map exactly so this test can split them without
splitting the process env).

Slow tier: the zero-overhead-off proof (fresh interpreter, RSDL_ off:
no relay import, no thread, no socket) and the ISSUE's headline
scenario — two real host processes on localhost with DISJOINT spool
trees (no shared filesystem) running a faulty shuffle, asserting the
driver's observability plane sees the remote host: federated metrics
sources, remote straggler records, a complete audit (ok=True, the
strict gate — not the unshared-spool "incomplete" verdict), remote
profile frames, and a live /healthz relay section.
"""

import json
import os
import re
import subprocess
import sys
import time
import zlib

import pytest

slow = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from ray_shuffling_data_loader_tpu.telemetry import relay


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _item(kind, name, data, offset=0, mode="append", crc=None):
    return {
        "kind": kind,
        "name": name,
        "mode": mode,
        "offset": offset,
        "data": data,
        "crc": _crc(data) if crc is None else crc,
    }


def _mkdirs(root, kinds=("metrics", "events", "audit", "tasks",
                         "capacity", "profiles")):
    out = {}
    for kind in kinds:
        d = os.path.join(str(root), kind)
        os.makedirs(d, exist_ok=True)
        out[kind] = d
    return out


# ---------------------------------------------------------------------------
# Receiver restamping (clock-skew safety — the satellite-5 regression)
# ---------------------------------------------------------------------------


def test_restamp_behind_clock_source_not_falsely_expired(
    tmp_path, monkeypatch
):
    """A live remote source whose wall clock runs far BEHIND the
    driver's writes snapshots that look ancient. Restamping with the
    receiver clock at arrival keeps it inside any sane ``max_age_s``
    window — the source is live, so it must contribute."""
    from ray_shuffling_data_loader_tpu.telemetry import export

    now = time.time()
    rec = {
        "source": {"role": "actor", "host": "wk", "pid": 7},
        "ts": now - 3600.0,  # producer clock an hour behind
        "metrics": {"x{}": {"kind": "counter", "value": 1.0}},
    }
    blob, skew = relay._restamp(
        json.dumps(rec).encode(), "10.0.0.2:abcd", now
    )
    out = json.loads(blob.decode())
    assert out["ts"] == pytest.approx(now)
    assert out["producer_ts"] == pytest.approx(now - 3600.0)
    assert skew == pytest.approx(3600.0)
    assert out["source"]["host"] == "10.0.0.2:abcd"
    assert out["source"]["relayed"] is True
    assert out["metrics"] == rec["metrics"]

    spool = tmp_path / "metrics"
    spool.mkdir()
    (spool / "metrics-actor-7.json").write_bytes(blob)
    monkeypatch.setenv("RSDL_METRICS_DIR", str(spool))
    assert len(export.load_records(max_age_s=60.0)) == 1


def test_restamp_ahead_clock_source_still_ages_out(tmp_path, monkeypatch):
    """A DEAD source whose clock ran AHEAD would, unstamped, stay under
    ``max_age_s`` forever. Restamped at arrival, the file's ts freezes
    at the last ship and ages out like any local source."""
    from ray_shuffling_data_loader_tpu.telemetry import export

    arrival = time.time() - 120.0  # last ship landed two minutes ago
    rec = {
        "source": {"role": "task", "host": "wk", "pid": 9},
        "ts": time.time() + 3600.0,  # producer clock an hour ahead
        "metrics": {"y{}": {"kind": "gauge", "value": 2.0}},
    }
    blob, _ = relay._restamp(
        json.dumps(rec).encode(), "10.0.0.3:beef", arrival
    )
    spool = tmp_path / "metrics"
    spool.mkdir()
    (spool / "metrics-task-9.json").write_bytes(blob)
    monkeypatch.setenv("RSDL_METRICS_DIR", str(spool))
    assert export.load_records(max_age_s=60.0) == []
    # Forensics survive: the producer's own clock is kept.
    kept = json.loads((spool / "metrics-task-9.json").read_bytes())
    assert kept["producer_ts"] > time.time()


def test_restamp_non_json_passes_through():
    blob, skew = relay._restamp(b"\x00not-json", "h:1", time.time())
    assert blob == b"\x00not-json"
    assert skew is None


# ---------------------------------------------------------------------------
# Sink mechanics
# ---------------------------------------------------------------------------


def test_sink_append_idempotent_overlap_and_gap(tmp_path):
    """Byte-exact concatenation under re-ships and reconnects: a
    duplicate delta is trimmed (no double records), a gap is bounced
    back with the sink's cursor (``want``), and the landed file is
    namespaced by source host so consumers' prefix filters match."""
    dirs = _mkdirs(tmp_path)
    sink = relay.RelaySink(dirs=dirs)
    host = "10.0.0.2:abcd"
    l1 = b'{"n":1}\n'
    l2 = b'{"n":2}\n'

    res = sink.ship(host, [_item("events", "events-42.ndjson", l1)])
    assert res["events/events-42.ndjson"] == {"acked": len(l1)}
    target = os.path.join(
        dirs["events"], "events-10.0.0.2_abcd-42.ndjson"
    )
    assert open(target, "rb").read() == l1

    # Exact duplicate (shipper retried before seeing the ack): trimmed.
    res = sink.ship(host, [_item("events", "events-42.ndjson", l1)])
    assert res["events/events-42.ndjson"] == {"acked": len(l1)}
    assert open(target, "rb").read() == l1

    # Gap (sink lost the file, shipper is ahead): bounced, not landed.
    res = sink.ship(
        host, [_item("events", "events-42.ndjson", l2, offset=100)]
    )
    assert res["events/events-42.ndjson"] == {"want": len(l1)}
    assert open(target, "rb").read() == l1

    # Partial overlap: ship [0, l1+l2) again — only the tail appends.
    res = sink.ship(
        host, [_item("events", "events-42.ndjson", l1 + l2, offset=0)]
    )
    assert res["events/events-42.ndjson"] == {"acked": len(l1 + l2)}
    assert open(target, "rb").read() == l1 + l2

    snap = sink.snapshot()
    assert snap[host]["ships"] == 4
    assert snap[host]["bytes"] == len(l1 + l2)


def test_sink_rejects_bad_crc_and_contains_bad_names(tmp_path):
    dirs = _mkdirs(tmp_path)
    sink = relay.RelaySink(dirs=dirs)
    res = sink.ship(
        "h:1",
        [_item("events", "events-1.ndjson", b'{"a":1}\n', crc=123)],
    )
    assert res["events/events-1.ndjson"] == {"error": "crc"}
    assert os.listdir(dirs["events"]) == []

    # A name trying to escape the spool dir (or not matching the kind's
    # prefix/suffix) is acked-and-dropped, never written.
    evil = "../events-1.ndjson"
    res = sink.ship("h:1", [_item("events", evil, b"x\n")])
    assert res[f"events/{evil}"] == {"acked": 2}
    assert os.listdir(dirs["events"]) == []
    assert not os.path.exists(os.path.join(str(tmp_path), "events-1.ndjson"))

    # A kind with no local home (audit off at the driver): acked so the
    # shipper advances — degraded, not wedged.
    dirs2 = dict(dirs, audit=None)
    sink2 = relay.RelaySink(dirs=dirs2)
    res = sink2.ship("h:1", [_item("audit", "audit-1.jsonl", b"y\n")])
    assert res["audit/audit-1.jsonl"] == {"acked": 2}


def test_sink_replace_restamps_metrics_snapshot(tmp_path):
    dirs = _mkdirs(tmp_path)
    sink = relay.RelaySink(dirs=dirs)
    rec = {
        "source": {"role": "task", "host": "wk", "pid": 5},
        "ts": 17.0,
        "metrics": {"m{}": {"kind": "counter", "value": 3.0}},
    }
    blob = json.dumps(rec).encode()
    res = sink.ship(
        "10.0.0.9:cafe",
        [_item("metrics", "metrics-task-5.json", blob, mode="replace")],
    )
    assert res["metrics/metrics-task-5.json"] == {"acked": len(blob)}
    target = os.path.join(
        dirs["metrics"], "metrics-10.0.0.9_cafe-task-5.json"
    )
    landed = json.loads(open(target).read())
    assert landed["ts"] == pytest.approx(time.time(), abs=30)
    assert landed["producer_ts"] == 17.0
    assert landed["source"]["host"] == "10.0.0.9:cafe"
    assert landed["source"]["relayed"] is True
    assert sink.snapshot()["10.0.0.9:cafe"]["skew_s"] > 0


def test_hello_skips_shared_dirs_and_reports_cursors(tmp_path):
    """The handshake: kinds whose spool dir IS the sink's dir (shared
    filesystem — dev/ino match) are skipped so nothing double-counts,
    and already-landed append files come back as byte cursors so a
    reconnecting shipper resumes instead of re-shipping."""
    sink_dirs = _mkdirs(tmp_path / "driver")
    worker_dirs = _mkdirs(tmp_path / "worker")
    sink = relay.RelaySink(dirs=sink_dirs)
    host = "10.0.0.2:abcd"

    # Pre-land 2 lines, as a prior connection would have.
    sink.ship(host, [_item("tasks", "tasks-77.ndjson", b"a\nb\n")])

    shared = dict(worker_dirs, events=sink_dirs["events"])
    reply = sink.hello(host, relay._dir_fingerprints(shared))
    assert reply["skip"] == ["events"]
    assert reply["cursors"] == {"tasks/tasks-77.ndjson": 4}

    # Fully disjoint dirs: nothing skipped.
    reply = sink.hello(host, relay._dir_fingerprints(worker_dirs))
    assert reply["skip"] == []


# ---------------------------------------------------------------------------
# Shipper → sink over the real transport (one process, split dirs)
# ---------------------------------------------------------------------------


def test_shipper_end_to_end_over_tcp(tmp_path):
    """Full round trip on the real actor transport: append deltas land
    byte-exact and namespaced, replace snapshots land restamped,
    incremental ships append only the tail, and a fresh shipper
    (reconnect) resumes from the hello cursors without duplicating a
    byte."""
    from ray_shuffling_data_loader_tpu.runtime.actor import ActorHandle

    sink_dirs = _mkdirs(tmp_path / "driver")
    worker_dirs = _mkdirs(tmp_path / "worker")
    host_id = "127.0.0.1:e2e0"

    ev = os.path.join(worker_dirs["events"], "events-11.ndjson")
    with open(ev, "w") as f:
        f.write('{"e":1}\n{"e":2}\n')
    mt = os.path.join(worker_dirs["metrics"], "metrics-task-11.json")
    with open(mt, "w") as f:
        json.dump({"source": {"host": "wk", "pid": 11}, "ts": 1.0,
                   "metrics": {}}, f)

    server = relay._SinkServer("127.0.0.1", dirs=sink_dirs)
    server.start()
    try:
        def mk_shipper():
            return relay._Shipper(
                host_id,
                str(tmp_path / "rt"),
                lambda: ActorHandle(server.address),
                dirs=worker_dirs,
            )

        shipper = mk_shipper()
        shipper._ship_cycle()  # direct drive: no thread, no timing
        landed_ev = os.path.join(
            sink_dirs["events"], "events-127.0.0.1_e2e0-11.ndjson"
        )
        assert open(landed_ev).read() == '{"e":1}\n{"e":2}\n'
        landed_mt = os.path.join(
            sink_dirs["metrics"], "metrics-127.0.0.1_e2e0-task-11.json"
        )
        assert json.load(open(landed_mt))["source"]["host"] == host_id
        assert shipper.ships == 1
        assert shipper.shipped_bytes > 0
        assert shipper.lag_bytes == 0

        # Incremental: one more line, one unchanged snapshot → only the
        # delta ships (the replace signature suppresses the re-send).
        with open(ev, "a") as f:
            f.write('{"e":3}\n')
        before = shipper.shipped_bytes
        shipper._ship_cycle()
        assert open(landed_ev).read() == '{"e":1}\n{"e":2}\n{"e":3}\n'
        assert shipper.shipped_bytes - before == len('{"e":3}\n')

        # Reconnect: a brand-new shipper (driver restart symmetric case
        # — all cursors lost) hellos, resumes, and duplicates nothing.
        shipper2 = mk_shipper()
        shipper2._ship_cycle()
        assert open(landed_ev).read() == '{"e":1}\n{"e":2}\n{"e":3}\n'
        assert shipper2.ship_errors == 0

        # The sink saw exactly one source host, fresh.
        snap = server.sink.snapshot()
        assert list(snap) == [host_id]
    finally:
        server.stop()


def test_shipper_drop_ahead_is_bounded_and_line_aligned(
    tmp_path, monkeypatch
):
    """Bounded buffering: a spool far beyond ``RSDL_RELAY_MAX_LAG_BYTES``
    is dropped forward to a line boundary (no torn records at the
    driver), the drop is counted, and repeated cycles drain the rest."""
    from ray_shuffling_data_loader_tpu.runtime.actor import ActorHandle

    monkeypatch.setenv("RSDL_RELAY_MAX_LAG_BYTES", "8192")
    monkeypatch.setenv("RSDL_RELAY_MAX_BATCH_BYTES", "4096")

    sink_dirs = _mkdirs(tmp_path / "driver")
    worker_dirs = _mkdirs(tmp_path / "worker")
    src = os.path.join(worker_dirs["tasks"], "tasks-5.ndjson")
    with open(src, "w") as f:
        for i in range(1500):
            f.write(json.dumps({"i": i, "pad": "x" * 20}) + "\n")
    src_bytes = open(src, "rb").read()
    assert len(src_bytes) > 3 * 8192

    server = relay._SinkServer("127.0.0.1", dirs=sink_dirs)
    server.start()
    try:
        shipper = relay._Shipper(
            "127.0.0.1:lag0",
            str(tmp_path / "rt"),
            lambda: ActorHandle(server.address),
            dirs=worker_dirs,
        )
        for _ in range(40):
            shipper._ship_cycle()
            if shipper.lag_bytes == 0 and shipper.ships > 1:
                break
        assert shipper.lag_bytes == 0
        assert shipper.dropped_bytes > 0
        landed = open(
            os.path.join(
                sink_dirs["tasks"], "tasks-127.0.0.1_lag0-5.ndjson"
            ),
            "rb",
        ).read()
        # Exactly the source's tail, starting on a fresh line.
        dropped = len(src_bytes) - len(landed)
        assert dropped == shipper.dropped_bytes
        assert src_bytes[dropped:] == landed
        assert src_bytes[dropped - 1:dropped] == b"\n"
        for line in landed.splitlines():
            json.loads(line)  # every landed record parses
    finally:
        server.stop()


def test_shipper_survives_sink_death_and_reresolves(tmp_path):
    """Relay death is degraded-not-wrong: cycles against a dead sink
    count ship_errors (→ /healthz, relay.ship_errors_total) and the
    shipper re-resolves; a new sink at a new address picks the stream
    back up from its hello cursors."""
    from ray_shuffling_data_loader_tpu.runtime.actor import ActorHandle

    sink_dirs = _mkdirs(tmp_path / "driver")
    worker_dirs = _mkdirs(tmp_path / "worker")
    ev = os.path.join(worker_dirs["events"], "events-3.ndjson")
    with open(ev, "w") as f:
        f.write("a\n")

    current = {"server": relay._SinkServer("127.0.0.1", dirs=sink_dirs)}
    current["server"].start()
    shipper = relay._Shipper(
        "127.0.0.1:die0",
        str(tmp_path / "rt"),
        lambda: ActorHandle(current["server"].address),
        dirs=worker_dirs,
    )
    shipper._ship_cycle()
    assert shipper.ships == 1

    current["server"].stop()
    with open(ev, "a") as f:
        f.write("b\n")
    shipper._cycle_guarded()  # dead sink: guarded, counted, no raise
    assert shipper.ship_errors == 1
    assert shipper._sink is None

    current["server"] = relay._SinkServer("127.0.0.1", dirs=sink_dirs)
    current["server"].start()
    try:
        shipper._ship_cycle()
        landed = os.path.join(
            sink_dirs["events"], "events-127.0.0.1_die0-3.ndjson"
        )
        assert open(landed).read() == "a\nb\n"
    finally:
        current["server"].stop()


# ---------------------------------------------------------------------------
# Zero overhead off (fresh interpreter)
# ---------------------------------------------------------------------------


@slow
def test_relay_off_never_imports_plane(tmp_path):
    """RSDL_RELAY unset: a fresh interpreter running a whole shuffle
    never imports the relay module, starts no shipper/sink thread, and
    leaves no kick file — the zero-overhead contract every gated plane
    in this repo proves the same way."""
    code = """
import os, sys, threading
for k in list(os.environ):
    if k.startswith("RSDL_"):
        del os.environ[k]
os.environ["JAX_PLATFORMS"] = "cpu"
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_file
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle

class C(BatchConsumer):
    def consume(self, rank, epoch, batches): pass
    def producer_done(self, rank, epoch): pass
    def wait_until_ready(self, epoch): pass
    def wait_until_all_epochs_done(self): pass

files = [generate_file(0, 0, 128, 1, os.getcwd())[0]]
runtime.init(num_workers=1)
shuffle(files, C(), num_epochs=1, num_reducers=1, num_trainers=1, seed=1)
assert not any(
    t.name.startswith("rsdl-relay") for t in threading.enumerate()
), "relay thread running while off"
runtime.shutdown()
assert (
    "ray_shuffling_data_loader_tpu.telemetry.relay" not in sys.modules
), "relay imported on a relay-off run"
kicks = [
    os.path.join(d, f)
    for d, _, fs in os.walk(os.getcwd())
    for f in fs
    if f == "kick"
]
assert not kicks, kicks
print("RELAY_ZERO_OVERHEAD_OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": _REPO},
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr
    assert "RELAY_ZERO_OVERHEAD_OK" in out.stdout


# ---------------------------------------------------------------------------
# Two hosts, no shared spool (the ISSUE headline scenario)
# ---------------------------------------------------------------------------

FED_HEAD_SCRIPT = r"""
import json, os, sys, time, urllib.request
sys.path.insert(0, {repo!r})
from ray_shuffling_data_loader_tpu import runtime, ShufflingDataset
from ray_shuffling_data_loader_tpu.data_generation import generate_data

ctx = runtime.init_cluster(advertise_host="127.0.0.1", num_workers=2)
with open({addr_file!r} + ".tmp", "w") as f:
    f.write(ctx.cluster.address)
os.rename({addr_file!r} + ".tmp", {addr_file!r})

deadline = time.time() + 60
while len(ctx.cluster.registry.call("hosts")) < 2:
    if time.time() > deadline:
        print("VERDICT: FAIL worker never joined", flush=True)
        sys.exit(1)
    time.sleep(0.2)

filenames, _ = generate_data(
    num_rows=2000, num_files=4, num_row_groups_per_file=1,
    max_row_group_skew=0.0, data_dir={data_dir!r},
)
ds = ShufflingDataset(
    filenames, num_epochs=2, num_trainers=1, batch_size=250, rank=0,
    num_reducers=4, seed=11, queue_name="q-fed",
)
ok = True
for epoch in range(2):
    ds.set_epoch(epoch)
    keys = sorted(k for b in ds for k in b["key"].tolist())
    if keys != list(range(2000)):
        ok = False
        print(f"VERDICT: FAIL epoch {{epoch}} keys wrong", flush=True)

from ray_shuffling_data_loader_tpu.telemetry import audit as _audit
from ray_shuffling_data_loader_tpu.telemetry import export, stragglers

spool = os.environ["RSDL_RUNTIME_DIR"]

def _remote(dirpath, prefix):
    try:
        return sorted(
            f for f in os.listdir(dirpath)
            if f.startswith(prefix + "127.0.0.1_")
        )
    except OSError:
        return []

# Wait for the worker's final flush-then-ship barriers to land: remote
# host-namespaced files under the DRIVER's spool tree, and a complete
# (strict-gate) audit: ok must be True — not the unshared-spool
# "incomplete" None verdict.
audit_ok = False
deadline = time.time() + 45
while time.time() < deadline:
    have = (
        _remote(os.path.join(spool, "metrics"), "metrics-")
        and _remote(os.path.join(spool, "metrics", "tasks"), "tasks-")
        and _remote(os.path.join(spool, "profiles"), "profile-")
        and _remote(os.environ["RSDL_AUDIT_DIR"], "audit-")
    )
    if have:
        verdicts = _audit.reconcile(range(2))
        audit_ok = bool(verdicts) and all(
            v.get("ok") is True for v in verdicts
        )
        if audit_ok:
            break
    time.sleep(0.5)

if not audit_ok:
    ok = False
    print(
        "VERDICT: FAIL audit not complete-ok: "
        + json.dumps(_audit.summary()), flush=True,
    )
if _audit.summary().get("ok") is not True:
    ok = False
    print("VERDICT: FAIL audit summary ok is not True", flush=True)

# Federated metrics: the aggregate must see >= 2 distinct source hosts
# (driver's hostname + the worker's cluster host id).
hosts = set()
for rec in export.load_records():
    src = rec.get("source") or {{}}
    hosts.add(str(src.get("host")))
if len(hosts) < 2:
    ok = False
    print(f"VERDICT: FAIL metric sources not federated: {{hosts}}",
          flush=True)
relayed = [
    rec for rec in export.load_records()
    if (rec.get("source") or {{}}).get("relayed")
]
if not relayed:
    ok = False
    print("VERDICT: FAIL no relayed metric records", flush=True)

# Remote straggler records fold into the driver-side analyzer.
task_dir = os.path.join(spool, "metrics", "tasks")
remote_task_files = _remote(task_dir, "tasks-")
remote_lines = 0
for f in remote_task_files:
    with open(os.path.join(task_dir, f)) as fh:
        remote_lines += sum(1 for ln in fh if ln.strip())
if remote_lines <= 0:
    ok = False
    print("VERDICT: FAIL no remote task records", flush=True)
analysis = stragglers.analyze()
if analysis["tasks_total"] < remote_lines:
    ok = False
    print("VERDICT: FAIL analyzer missing remote tasks", flush=True)

# Live endpoints: /healthz shows a fresh remote source on the sink;
# /stragglers serves the federated fold.
port = int(os.environ["RSDL_OBS_PORT"])
def _get(path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{{port}}{{path}}", timeout=10
    ) as r:
        return json.loads(r.read().decode())
hz = _get("/healthz")
rl = hz.get("relay") or {{}}
if rl.get("role") != "sink" or not rl.get("hosts"):
    ok = False
    print(f"VERDICT: FAIL /healthz relay section: {{rl}}", flush=True)
elif any(rec.get("stale") for rec in rl["hosts"].values()):
    ok = False
    print(f"VERDICT: FAIL relay source stale: {{rl}}", flush=True)
sg = _get("/stragglers")
if sg.get("tasks_total", 0) < remote_lines:
    ok = False
    print("VERDICT: FAIL /stragglers missing remote tasks", flush=True)
cr = _get("/critical")
if cr.get("tasks_total", 0) < remote_lines:
    ok = False
    print("VERDICT: FAIL /critical missing remote tasks", flush=True)

# Keep the federated spool for the post-hoc epoch report (the session
# owner removes the runtime dir on shutdown).
import shutil
shutil.copytree(task_dir, os.path.join({keep_dir!r}, "tasks"))
with open(os.path.join({keep_dir!r}, "meta.json"), "w") as f:
    json.dump({{"remote_lines": remote_lines}}, f)

print("VERDICT: " + ("PASS" if ok else "FAIL"), flush=True)
runtime.shutdown()
"""

FED_WORKER_SCRIPT = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.runtime import cluster

deadline = time.time() + 60
while not os.path.exists({addr_file!r}):
    if time.time() > deadline:
        sys.exit(2)
    time.sleep(0.1)
with open({addr_file!r}) as f:
    address = f.read().strip()
ctx = runtime.init(address=address, num_workers=2)
print(f"joined {{ctx.cluster.host_id}}", flush=True)
cluster.serve_forever()
runtime.shutdown()
"""


@slow
def test_two_host_federation_without_shared_spool(tmp_path):
    """The ISSUE's headline: two real host processes on localhost with
    fully DISJOINT spool trees (each session owner creates its own
    runtime dir; audit dirs are explicitly split) run a 2-epoch shuffle
    under low-probability capped fault injection. The driver's obs
    plane must see the remote host exactly as if the filesystem were
    shared: federated metric sources (>= 2 hosts), remote straggler
    records in the live analyzer and /stragglers, remote profile
    frames, a COMPLETE audit (ok=True — the strict gate; without the
    relay this run yields the unshared-spool "incomplete" verdict), a
    fresh /healthz relay section, and a post-hoc epoch report whose
    straggler table folds the remote records."""
    addr_file = str(tmp_path / "head_address")
    data_dir = str(tmp_path / "data")
    keep_dir = tmp_path / "keep"
    keep_dir.mkdir()
    head_audit = tmp_path / "audit-head"
    worker_audit = tmp_path / "audit-worker"
    head_audit.mkdir()
    worker_audit.mkdir()

    base = {
        k: v for k, v in os.environ.items() if not k.startswith("RSDL_")
    }
    base["JAX_PLATFORMS"] = "cpu"
    common = dict(
        base,
        RSDL_ADVERTISE_HOST="127.0.0.1",
        RSDL_METRICS="1",
        RSDL_RELAY="auto",
        RSDL_AUDIT="1",
        RSDL_PROFILE="1",
        # Low-probability, attempt-capped chaos on both hosts: the run
        # must recover (retries) AND the audit must still reconcile
        # complete across the relay.
        RSDL_FAULTS="task.map:crash-entry:0.2x2,task.reduce:crash-exit:0.2x2",
        RSDL_FAULTS_SEED="1119",
    )
    import socket as _socket

    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    obs_port = probe.getsockname()[1]
    probe.close()
    head_env = dict(
        common,
        RSDL_AUDIT_DIR=str(head_audit),
        RSDL_OBS_PORT=str(obs_port),
    )
    worker_env = dict(common, RSDL_AUDIT_DIR=str(worker_audit))

    head_log = tmp_path / "head.log"
    worker_log = tmp_path / "worker.log"
    with open(head_log, "w") as hf, open(worker_log, "w") as wf:
        head = subprocess.Popen(
            [sys.executable, "-c", FED_HEAD_SCRIPT.format(
                repo=_REPO,
                addr_file=addr_file,
                data_dir=data_dir,
                keep_dir=str(keep_dir),
            )],
            stdout=hf,
            stderr=subprocess.STDOUT,
            env=head_env,
        )
        worker = subprocess.Popen(
            [sys.executable, "-c", FED_WORKER_SCRIPT.format(
                repo=_REPO, addr_file=addr_file
            )],
            stdout=wf,
            stderr=subprocess.STDOUT,
            env=worker_env,
        )
        try:
            head.wait(timeout=420)
        finally:
            head.kill()
            worker.kill()
            head.wait()
            worker.wait()

    head_out = head_log.read_text()
    assert "VERDICT: PASS" in head_out, (
        f"head output:\n{head_out}\n--- worker output:\n"
        f"{worker_log.read_text()}"
    )

    # Post-hoc epoch report over the federated task spool: the
    # straggler table must fold the remote host's records too.
    meta = json.loads((keep_dir / "meta.json").read_text())
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "tools", "epoch_report.py"),
            "--task-records", str(keep_dir / "tasks"),
            "--json",
        ],
        capture_output=True,
        text=True,
        timeout=120,
        env=dict(base),
    )
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout)
    rows = report.get("stragglers") or []
    assert rows, report
    assert sum(int(r.get("tasks", 0)) for r in rows) >= meta["remote_lines"]
