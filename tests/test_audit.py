"""Data-correctness audit layer tests (ISSUE 2 acceptance).

End-to-end digests through the real map/reduce/delivery pipeline: every
epoch's map == reduce == delivered coverage, an injected row-drop caught
with the failing epoch identified, fixed-seed delivered digests
reproducible across invocations, and the audit-off hot path doing no
digest work at all."""

import collections
import os

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_data
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle
from ray_shuffling_data_loader_tpu.telemetry import audit, metrics

_AUDIT_ENV = ("RSDL_AUDIT", "RSDL_AUDIT_DIR", "RSDL_METRICS")


@pytest.fixture(scope="module")
def audit_runtime(tmp_path_factory):
    """A runtime whose workers were spawned AFTER auditing was enabled,
    so map/reduce tasks inherit the env and spool digest records."""
    saved = {k: os.environ.get(k) for k in _AUDIT_ENV}
    spool = str(tmp_path_factory.mktemp("audit-spool"))
    os.environ["RSDL_AUDIT"] = "1"
    os.environ["RSDL_AUDIT_DIR"] = spool
    os.environ["RSDL_METRICS"] = "1"
    audit.refresh_from_env()
    metrics.refresh_from_env()
    audit.reset(clear_spool=True)
    metrics.reset()
    ctx = runtime.init(num_workers=2)
    yield ctx
    runtime.shutdown()
    audit.reset(clear_spool=True)
    audit.clear_faults()
    metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    audit.refresh_from_env()
    metrics.refresh_from_env()


@pytest.fixture(scope="module")
def audit_dataset(audit_runtime, tmp_path_factory):
    data_dir = tmp_path_factory.mktemp("audit-data")
    filenames, num_bytes = generate_data(
        num_rows=2000,
        num_files=4,
        num_row_groups_per_file=2,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )
    assert num_bytes > 0
    return filenames


class CollectingConsumer(BatchConsumer):
    def __init__(self):
        self.keys = collections.defaultdict(list)

    def consume(self, rank, epoch, batches):
        store = runtime.get_context().store
        for ref in batches:
            cb = store.get_columns(ref)
            self.keys[(epoch, rank)].extend(cb["key"].tolist())
            store.free(ref)

    def producer_done(self, rank, epoch):
        pass

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


def test_digest_math_order_invariant_and_order_sensitive():
    keys = np.arange(1000, dtype=np.int64)
    perm = np.random.default_rng(0).permutation(keys)
    a, b = audit.StreamDigest(), audit.StreamDigest()
    a.update(keys)
    b.update(perm)
    # Coverage ignores order; same multiset -> same (count, xor, sum).
    assert a.coverage() == b.coverage()
    # Associativity: folding two halves == one pass.
    c, lo, hi = audit.StreamDigest(), audit.StreamDigest(), audit.StreamDigest()
    lo.update(keys[:400])
    hi.update(keys[400:])
    c.merge(lo)
    c.merge(hi)
    assert c.coverage() == a.coverage()
    # seq is order-SENSITIVE at matched positions.
    a2, b2 = audit.StreamDigest(), audit.StreamDigest()
    a2.update(keys, offset=0)
    b2.update(perm, offset=0)
    assert a2.seq != b2.seq
    # Position hashing is domain-separated from key hashing: with row-id
    # keys (key == position) a shared domain would make the sorted
    # stream digest to 0 and its reversal cancel to the same value.
    assert a2.seq != 0
    r2 = audit.StreamDigest()
    r2.update(keys[::-1], offset=0)
    assert r2.seq not in (0, a2.seq)
    # A single crossed swap (key i at position j, key j at position i)
    # must change seq.
    swapped = keys.copy()
    swapped[3], swapped[700] = swapped[700], swapped[3]
    s2 = audit.StreamDigest()
    s2.update(swapped, offset=0)
    assert s2.seq != a2.seq
    # A dropped row breaks coverage.
    d = audit.StreamDigest()
    d.update(keys[:-1])
    assert d.coverage() != a.coverage()
    # Int32/int64 key VALUES hash identically (decode narrowing must not
    # split the digest equality).
    e = audit.StreamDigest()
    e.update(keys.astype(np.int32))
    assert e.coverage() == a.coverage()


def test_multi_epoch_exactly_once_verdicts(audit_runtime, audit_dataset):
    """Acceptance: a multi-epoch end-to-end run reports map == reduce ==
    delivered digests and row counts for every epoch, and the audit.*
    counters land in the PR-1 metrics registry."""
    consumer = CollectingConsumer()
    num_epochs = 3
    shuffle(
        audit_dataset,
        consumer,
        num_epochs=num_epochs,
        num_reducers=5,
        num_trainers=2,
        seed=11,
    )
    verdicts = audit.verdicts()
    assert [v["epoch"] for v in verdicts] == list(range(num_epochs))
    for v in verdicts:
        assert v["ok"] is True, v
        assert v["rows_mapped"] == 2000
        assert v["rows_reduced"] == 2000
        assert v["rows_delivered"] == 2000
        assert v["map_digest"] == v["reduce_digest"] == v["delivered_digest"]
    snap = metrics.registry.snapshot()
    assert snap["audit.rows_mapped"] == num_epochs * 2000
    assert snap["audit.rows_delivered"] == num_epochs * 2000
    assert snap["audit.digest_mismatch"] == 0.0
    assert snap[metrics.format_key("audit.epoch_ok", {"epoch": 2})] == 1.0


def test_shuffle_quality_metrics(audit_runtime, audit_dataset):
    """A healthy seeded reshuffle looks random by the numbers: near-zero
    adjacent-pair retention, mean displacement near 1/3 (the uniform-
    permutation expectation), and near-uniform source-file entropy."""
    consumer = CollectingConsumer()
    shuffle(
        audit_dataset,
        consumer,
        num_epochs=3,
        num_reducers=4,
        num_trainers=1,
        seed=7,
    )
    verdicts = audit.verdicts()
    assert verdicts[0]["adjacent_pair_retention"] is None  # no prior epoch
    for v in verdicts[1:]:
        assert v["adjacent_pair_retention"] < 0.05
        assert 0.15 < v["mean_normalized_displacement"] < 0.55
    for v in verdicts:
        assert 0.9 < v["source_entropy_mean"] <= 1.0
        assert v["source_entropy_min"] > 0.8


def test_shuffle_quality_metrics_block_plan(
    audit_runtime, tmp_path_factory, monkeypatch
):
    """The block plan family's quality-vs-pruning tradeoff gets a
    regression FENCE, not a BENCHLOG paragraph (ISSUE 12): with
    RSDL_AUDIT on, a block:1 run at a bench-like shape (blocks per file
    = 2x reducers) emits retention/displacement/entropy per epoch, the
    gauges carry the plan label, and every metric stays within the
    bounds documented in TUNING.md — and within range of the same
    shape under rowwise."""
    data_dir = tmp_path_factory.mktemp("audit-block-data")
    filenames, _ = generate_data(
        num_rows=2000,
        num_files=4,
        num_row_groups_per_file=8,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )

    def run(plan_env):
        if plan_env is None:
            monkeypatch.delenv("RSDL_SHUFFLE_PLAN", raising=False)
        else:
            monkeypatch.setenv("RSDL_SHUFFLE_PLAN", plan_env)
        consumer = CollectingConsumer()
        shuffle(
            filenames, consumer, num_epochs=3, num_reducers=4,
            num_trainers=1, seed=13,
        )
        return audit.verdicts()

    block = run("block")
    # Per-epoch emission with RSDL_AUDIT on: every epoch reconciled ok
    # and carries the quality numbers (retention/displacement need a
    # prior epoch by definition).
    assert [v["epoch"] for v in block] == [0, 1, 2]
    for v in block:
        assert v["ok"] is True
        assert v["source_entropy_mean"] is not None
        assert v["source_entropy_min"] is not None
    for v in block[1:]:
        assert v["adjacent_pair_retention"] is not None
        assert v["mean_normalized_displacement"] is not None
    # The quality gauges are plan-labeled (observability.md vocabulary).
    snap = metrics.registry.snapshot()
    assert (
        metrics.format_key(
            "audit.source_entropy_mean", {"epoch": 1, "plan": "block:1"}
        )
        in snap
    )
    # Documented bounds (TUNING.md RSDL_SHUFFLE_PLAN row): with blocks
    # per file >= 2R, block:1 keeps a healthy reshuffle profile...
    for v in block[1:]:
        assert v["adjacent_pair_retention"] < 0.05
        assert 0.15 < v["mean_normalized_displacement"] < 0.55
    for v in block:
        assert v["source_entropy_min"] > 0.8
    # ... and stays within range of rowwise at the same shape (the
    # per-reducer file mix loses at most 0.1 normalized entropy).
    rowwise = run(None)
    for vb, vr in zip(block, rowwise):
        assert vb["source_entropy_mean"] > vr["source_entropy_mean"] - 0.1
    for vb, vr in zip(block[1:], rowwise[1:]):
        assert (
            abs(
                vb["mean_normalized_displacement"]
                - vr["mean_normalized_displacement"]
            )
            < 0.2
        )


def test_injected_row_drop_detected(audit_runtime, audit_dataset):
    """Acceptance: a test-only delivery fault (one row silently dropped)
    is detected as a digest mismatch with the failing epoch identified —
    the healthy epoch stays clean."""
    audit.inject_fault("drop-row", epoch=1)
    try:
        consumer = CollectingConsumer()
        shuffle(
            audit_dataset,
            consumer,
            num_epochs=2,
            num_reducers=4,
            num_trainers=1,
            seed=3,
        )
    finally:
        audit.clear_faults()
    # The fault is real: the consumer saw 1999 rows in epoch 1.
    assert len(consumer.keys[(1, 0)]) == 1999
    by_epoch = {v["epoch"]: v for v in audit.verdicts()}
    assert by_epoch[0]["ok"] is True
    assert by_epoch[1]["ok"] is False
    assert by_epoch[1]["mismatch"] == ["delivered"]
    assert by_epoch[1]["rows_delivered"] == 1999
    assert by_epoch[1]["rows_mapped"] == 2000
    assert metrics.registry.snapshot()["audit.digest_mismatch"] == 1.0
    summary = audit.summary()
    assert summary["ok"] is False
    assert summary["mismatch_epochs"] == [1]


def test_strict_mode_raises(audit_runtime, audit_dataset, monkeypatch):
    monkeypatch.setenv("RSDL_AUDIT_STRICT", "1")
    audit.inject_fault("drop-row", epoch=0)
    try:
        with pytest.raises(audit.AuditError, match=r"epoch\(s\) \[0\]"):
            shuffle(
                audit_dataset,
                CollectingConsumer(),
                num_epochs=1,
                num_reducers=3,
                num_trainers=1,
                seed=2,
            )
    finally:
        audit.clear_faults()


def test_fixed_seed_delivered_digests_reproducible(
    audit_runtime, audit_dataset
):
    """Acceptance: two invocations with the same seed produce identical
    per-epoch delivered digests — including the order-sensitive sequence
    digest — and a different seed produces different ones."""

    def run(seed):
        shuffle(
            audit_dataset,
            CollectingConsumer(),
            num_epochs=2,
            num_reducers=4,
            num_trainers=2,
            seed=seed,
        )
        return [
            (v["delivered_digest"], v["delivered_seq"])
            for v in audit.verdicts()
        ]

    first = run(5)
    second = run(5)
    other = run(6)
    assert first == second
    # Same rows (coverage equal), different permutation (seq differs).
    assert [d for d, _ in other] == [d for d, _ in first]
    assert [s for _, s in other] != [s for _, s in first]


def test_index_schedule_audited(audit_runtime, audit_dataset):
    """The steady-state index schedule (plan + sparse gather from the
    decode cache) carries the same digest equality as the materialized
    path — the audit covers both schedules."""
    log = []
    shuffle(
        audit_dataset,
        CollectingConsumer(),
        num_epochs=3,
        num_reducers=4,
        num_trainers=1,
        seed=5,
        cache_decoded=True,
        schedule_log=log,
    )
    assert dict(log)[1] == "index"  # the fast path actually engaged
    for v in audit.verdicts():
        assert v["ok"] is True, v
        assert v["rows_delivered"] == 2000


def test_dataset_consumed_side_folds(audit_runtime, audit_dataset):
    """The trainer-side dataset records consumed digests; with the
    consumer in-process the verdict folds all four sides."""
    from ray_shuffling_data_loader_tpu import ShufflingDataset

    ds = ShufflingDataset(
        list(audit_dataset),
        num_epochs=2,
        num_trainers=1,
        batch_size=300,
        rank=0,
        num_reducers=4,
        seed=9,
        queue_name="audit-consume",
    )
    for epoch in range(2):
        ds.set_epoch(epoch)
        keys = [k for b in ds for k in b["key"].tolist()]
        assert sorted(keys) == list(range(2000))
    for v in audit.verdicts():
        assert v["ok"] is True, v
        assert v["rows_consumed"] == 2000
        assert v["consumed_digest"] == v["delivered_digest"]


def test_reconcile_dedups_retried_task_records(monkeypatch):
    """Cluster failover can execute a map/reduce task twice (the first
    agent died after flushing its digest record); reconcile must fold
    each logical unit of work once, not report a false mismatch."""
    monkeypatch.delenv("RSDL_AUDIT_DIR", raising=False)
    audit.reset()
    try:
        lo = {"key": np.arange(50)}
        hi = {"key": np.arange(50, 100)}
        both = {"key": np.arange(100)}
        audit.record_map(0, 0, both, per_reducer=[50, 50])
        audit.record_map(0, 0, both, per_reducer=[50, 50])  # retried
        audit.record_reduce(0, 0, lo)
        audit.record_reduce(0, 0, lo)  # retried attempt
        audit.record_reduce(0, 1, hi)
        audit.record_deliver(0, 0, 0, lo, 0)
        audit.record_deliver(0, 1, 0, hi, 50)
        (v,) = audit.reconcile([0])
        assert v["ok"] is True, v
        assert v["rows_mapped"] == 100
        assert v["rows_reduced"] == 100
    finally:
        audit.reset()


def test_reconcile_missing_worker_records_is_incomplete_not_mismatch(
    monkeypatch,
):
    """Deliver records without ANY map/reduce records (multi-host run
    whose spool dir is not shared) is an incomplete audit, not a data
    defect: ok=None with the remedy, never a strict-mode abort."""
    monkeypatch.delenv("RSDL_AUDIT_DIR", raising=False)
    monkeypatch.setenv("RSDL_AUDIT_STRICT", "1")
    audit.reset()
    try:
        audit.record_deliver(0, 0, 0, {"key": np.arange(10)}, 0)
        (v,) = audit.reconcile([0])  # strict: must not raise
        assert v["ok"] is None
        assert "RSDL_AUDIT_DIR" in v["detail"]
        assert v["rows_delivered"] == 10
        # Zero audited epochs must not read as a pass.
        assert audit.summary(reconcile_if_needed=False)["ok"] is None
    finally:
        audit.reset()


def test_summary_none_when_nothing_audited(monkeypatch):
    monkeypatch.delenv("RSDL_AUDIT_DIR", raising=False)
    audit.reset()
    try:
        assert audit.summary()["ok"] is None
    finally:
        audit.reset()


def test_audit_off_is_noop(tmp_path):
    """No digest work when RSDL_AUDIT is unset: record sites early-return
    and no spool file is created (the enabled() gate is the only cost on
    the hot path)."""
    saved = {k: os.environ.get(k) for k in _AUDIT_ENV}
    os.environ.pop("RSDL_AUDIT", None)
    os.environ["RSDL_AUDIT_DIR"] = str(tmp_path / "spool")
    audit.refresh_from_env()
    try:
        assert not audit.enabled()
        # Sites all guard on enabled(); even called directly, safe_flush
        # must not touch the filesystem while disabled.
        audit.safe_flush()
        assert not os.path.exists(str(tmp_path / "spool"))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        audit.refresh_from_env()
