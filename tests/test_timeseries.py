"""Timeseries-history tests (ISSUE 7): ring-buffer wraparound, counter
rates (including reset handling across a source restart — a pid change
must never produce a negative rate), query name aliases / windowing,
append-only persistence, sampler thread lifecycle, and the
zero-overhead proof for the whole temporal plane (no sampler thread,
no event files, no module import when the env gates are unset)."""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from ray_shuffling_data_loader_tpu.telemetry import export, metrics
from ray_shuffling_data_loader_tpu.telemetry import timeseries

_ENV = ("RSDL_METRICS", "RSDL_METRICS_DIR", "RSDL_OBS_PORT", "RSDL_TS")


@pytest.fixture
def ts_env(tmp_path):
    """Metrics on, spooling to a per-test dir, timeseries state reset —
    fully unwound on teardown (function-scoped per the obs test
    convention)."""
    saved = {k: os.environ.get(k) for k in _ENV}
    spool = str(tmp_path / "metrics-spool")
    os.environ["RSDL_METRICS"] = "1"
    os.environ["RSDL_METRICS_DIR"] = spool
    os.environ.pop("RSDL_OBS_PORT", None)
    os.environ.pop("RSDL_TS", None)
    metrics.refresh_from_env()
    metrics.reset()
    timeseries.reset()
    yield spool
    timeseries.stop()
    timeseries.reset()
    metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    metrics.refresh_from_env()


def _write_record(spool, pid, role, ts, typed):
    os.makedirs(spool, exist_ok=True)
    with open(os.path.join(spool, f"metrics-{role}-{pid}.json"), "w") as f:
        json.dump(
            {
                "source": {
                    "role": role,
                    "host": socket.gethostname(),
                    "pid": pid,
                },
                "ts": ts,
                "metrics": typed,
            },
            f,
        )


def test_ring_wraparound(ts_env):
    timeseries.reset(capacity_override=4)
    metrics.registry.counter("wrap.rows").inc(1)
    stamps = []
    for i in range(7):
        stamps.append(100.0 + i)
        timeseries.sample_now(now=stamps[-1])
    samples = timeseries.samples()
    # Capacity held, oldest rolled off, order preserved.
    assert len(samples) == 4
    assert [s["ts"] for s in samples] == stamps[-4:]


def test_counter_rate_between_samples(ts_env):
    counter = metrics.registry.counter("rate.rows")
    counter.inc(10)
    first = timeseries.sample_now(now=1000.0)
    # The very first sample has no previous to rate against.
    assert "rate" not in first["metrics"]["rate.rows"]
    counter.inc(10)
    second = timeseries.sample_now(now=1002.0)
    entry = second["metrics"]["rate.rows"]
    assert entry["value"] == 20.0
    assert entry["rate"] == pytest.approx(5.0)


def test_counter_reset_across_source_restart_never_negative(ts_env):
    """A restarted source (new pid; the old spool file expired or was
    cleaned) can only LOWER the merged cumulative value — the sampler
    must treat the drop as a restart-from-zero, not a negative rate."""
    _write_record(
        ts_env, 111, "task", time.time(),
        {"restart.rows": {"kind": "counter", "value": 100.0}},
    )
    timeseries.sample_now(now=2000.0)
    # The worker restarts: old spool file gone, new pid starts from 0.
    os.unlink(os.path.join(ts_env, "metrics-task-111.json"))
    _write_record(
        ts_env, 222, "task", time.time(),
        {"restart.rows": {"kind": "counter", "value": 6.0}},
    )
    sample = timeseries.sample_now(now=2002.0)
    entry = sample["metrics"]["restart.rows"]
    assert entry["value"] == 6.0
    # delta = cur (restart), never cur - prev = -94.
    assert entry["rate"] == pytest.approx(3.0)
    assert all(
        e.get("rate", 0.0) >= 0.0 for e in sample["metrics"].values()
    )


def test_histogram_windowed_view(ts_env):
    hist = metrics.registry.histogram("lat")
    hist.observe(1.0)
    timeseries.sample_now(now=3000.0)
    hist.observe(3.0)
    hist.observe(5.0)
    sample = timeseries.sample_now(now=3002.0)
    entry = sample["metrics"]["lat"]
    assert entry["count"] == 3
    assert entry["rate"] == pytest.approx(1.0)  # 2 new obs / 2 s
    assert entry["window_mean"] == pytest.approx(4.0)  # (3+5)/2


def test_series_query_aliases_window_and_sources(ts_env):
    counter = metrics.registry.counter("shuffle.map_rows")
    _write_record(
        ts_env, 333, "task", time.time(),
        {"shuffle.map_rows": {"kind": "counter", "value": 7.0}},
    )
    for i in range(3):
        counter.inc(5)
        timeseries.sample_now(now=4000.0 + i)
    # Prometheus alias and raw name both match.
    for name in ("rsdl_shuffle_map_rows", "shuffle.map_rows"):
        series = timeseries.series(name=name, now=4002.0)
        assert "shuffle.map_rows" in series
        assert len(series["shuffle.map_rows"]) == 3
        # source= breakdown keys excluded by default...
        assert all("source=" not in k for k in series)
    # ...and included on request.
    series = timeseries.series(
        name="shuffle.map_rows", include_sources=True, now=4002.0
    )
    assert any("source=" in k for k in series)
    # Trailing window keeps only fresh points.
    series = timeseries.series(
        name="shuffle.map_rows", window_s=1.5, now=4002.0
    )
    assert len(series["shuffle.map_rows"]) == 2


def test_persisted_append_only(ts_env):
    metrics.registry.counter("persist.rows").inc(2)
    timeseries.sample_now(now=5000.0)
    timeseries.sample_now(now=5001.0)
    path = timeseries.persist_path()
    assert path and os.path.exists(path)
    loaded = timeseries.load_persisted()
    assert [s["ts"] for s in loaded] == [5000.0, 5001.0]
    assert loaded[1]["metrics"]["persist.rows"]["value"] == 2.0


def test_sampler_thread_lifecycle(ts_env):
    metrics.registry.counter("live.rows").inc(1)
    timeseries.start(period=0.05)
    assert timeseries.running()
    deadline = time.time() + 10
    while time.time() < deadline and not timeseries.samples():
        time.sleep(0.02)
    assert timeseries.samples(), "sampler never sampled"
    timeseries.stop()
    assert not timeseries.running()
    assert not any(
        t.name == "rsdl-ts-sampler" for t in threading.enumerate()
    )


def test_start_noop_when_metrics_off(ts_env):
    metrics.disable()
    timeseries.start(period=0.05)
    assert not timeseries.running()


_ZERO_OVERHEAD_SCRIPT = r"""
import os, sys, threading
for k in ("RSDL_METRICS", "RSDL_OBS_PORT", "RSDL_TS", "RSDL_METRICS_DIR",
          "RSDL_EVENTS_DIR", "RSDL_TRACE", "RSDL_AUDIT"):
    os.environ.pop(k, None)
os.environ["JAX_PLATFORMS"] = "cpu"
from ray_shuffling_data_loader_tpu import runtime
ctx = runtime.init(num_workers=1)
fut = runtime.submit(len, [1, 2, 3])
assert fut.result(timeout=120) == 3
# No temporal-plane module was ever imported (no import cost) ...
for mod in ("timeseries", "events", "stragglers", "obs_server"):
    name = "ray_shuffling_data_loader_tpu.telemetry." + mod
    assert name not in sys.modules, name
# ... no sampler thread ...
assert not any(
    t.name == "rsdl-ts-sampler" for t in threading.enumerate()
)
# ... and no event/task spool dirs in the session.
for sub in ("events", os.path.join("metrics", "tasks"),
            os.path.join("metrics", "ts")):
    assert not os.path.isdir(os.path.join(ctx.runtime_dir, sub)), sub
runtime.shutdown()
print("ZERO-OVERHEAD-OK")
"""


def test_zero_overhead_when_disabled():
    """ISSUE 7 acceptance: with RSDL_OBS_PORT/RSDL_METRICS unset there
    is no sampler thread, no event files, and no import cost — proven
    in a fresh interpreter (this test process has long since imported
    the modules)."""
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith("RSDL_")
    }
    proc = subprocess.run(
        [sys.executable, "-c", _ZERO_OVERHEAD_SCRIPT],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr
    assert "ZERO-OVERHEAD-OK" in proc.stdout
