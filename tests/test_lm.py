"""CausalLM family: forward contract, causality, and the dp x sp
sequence-parallel path matching the dense lowering."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax_compat import needs_toplevel_shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_shuffling_data_loader_tpu.models import (
    CausalLM,
    next_token_loss,
    synthetic_tokens,
)
from ray_shuffling_data_loader_tpu.ops import make_ring_attention

VOCAB, SEQ = 32, 64


def _model(**kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("max_seq_len", SEQ)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_layers", 1)
    kw.setdefault("num_heads", 2)
    kw.setdefault("compute_dtype", jnp.float32)
    return CausalLM(**kw)


def test_forward_contract_and_causality():
    model = _model()
    tokens = jnp.asarray(synthetic_tokens(2, SEQ, VOCAB, seed=1))
    params = model.init(jax.random.key(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, SEQ, VOCAB)
    assert bool(jnp.all(jnp.isfinite(logits)))
    # Causality: changing a future token must not change earlier logits.
    perturbed = tokens.at[:, SEQ // 2 :].set(
        (tokens[:, SEQ // 2 :] + 1) % VOCAB
    )
    logits_p = model.apply(params, perturbed)
    np.testing.assert_allclose(
        np.asarray(logits[:, : SEQ // 2]),
        np.asarray(logits_p[:, : SEQ // 2]),
        rtol=1e-5,
        atol=1e-5,
    )
    assert not np.allclose(
        np.asarray(logits[:, SEQ // 2 :]), np.asarray(logits_p[:, SEQ // 2 :])
    )


@needs_toplevel_shard_map
def test_sequence_parallel_matches_dense():
    """Same params under the dp x sp ring schedule and the dense lowering."""
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "sp"))
    tokens = jnp.asarray(synthetic_tokens(4, SEQ, VOCAB, seed=2))
    dense = _model()
    params = dense.init(jax.random.key(1), tokens)
    want = dense.apply(params, tokens)
    sp = _model(
        attention_fn=make_ring_attention(
            mesh, "sp", causal=True, batch_axis="data"
        )
    )
    tokens_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P("data", "sp"))
    )
    got = sp.apply(params, tokens_sharded)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_next_token_loss_learns():
    import optax

    model = _model(num_layers=2, embed_dim=32, num_heads=4)
    tokens = jnp.asarray(synthetic_tokens(8, SEQ, VOCAB, seed=3))
    params = model.init(jax.random.key(2), tokens)
    optimizer = optax.adam(3e-3)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(model.apply(p, tokens), tokens)
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(15):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses
