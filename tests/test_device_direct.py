"""Device-direct delivery (ISSUE 8): layout-descriptor round-trips
through the store (disk + wire formats), batch-grid alignment math,
bit-identical streams with the layout on vs off, partial-final-batch
handling, and the staged-vs-delivered audit reconcile on the new path."""

import os

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu.runtime.store import (
    DEVICE_BATCH_KIND,
    PACKED_COLUMN,
    ColumnBatch,
    device_batch_rows,
    is_device_batch,
    iter_packed_batches,
    logical_columns,
    map_segment_file,
    serialize_columns,
    serialize_columns_vectored,
)

LABEL = "labels"


def _descriptor(names, dtypes, batch):
    return {
        "kind": DEVICE_BATCH_KIND,
        "batch": int(batch),
        "columns": list(names),
        "dtypes": [np.dtype(d).str for d in dtypes],
    }


def _packed_segment(m=3, batch=8, seed=0):
    """A [m, n_cols, batch] packed matrix + its logical truth."""
    rng = np.random.default_rng(seed)
    names = ["a", "b", LABEL]
    dtypes = [np.int32, np.int32, np.float32]
    logical = {
        "a": rng.integers(0, 1 << 20, m * batch).astype(np.int32),
        "b": rng.integers(0, 1 << 20, m * batch).astype(np.int32),
        LABEL: rng.random(m * batch).astype(np.float32),
    }
    mat = np.empty((m, len(names), batch), np.int32)
    for b in range(m):
        for i, n in enumerate(names):
            mat[b, i] = (
                logical[n][b * batch : (b + 1) * batch].view(np.int32)
            )
    return mat, logical, _descriptor(names, dtypes, batch)


# ---------------------------------------------------------------------------
# Layout descriptor round-trips
# ---------------------------------------------------------------------------


def test_layout_roundtrip_store_publish(local_runtime):
    """create_columns(layout=...) -> seal -> get_columns preserves the
    descriptor, and the per-batch views reconstruct the logical columns
    exactly (zero-copy bit views)."""
    from ray_shuffling_data_loader_tpu import runtime

    store = runtime.get_context().store
    mat, logical, descriptor = _packed_segment()
    pending = store.create_columns(
        {PACKED_COLUMN: (mat.shape, np.dtype(np.int32))},
        layout=descriptor,
    )
    try:
        np.copyto(pending.columns[PACKED_COLUMN], mat)
        ref = pending.seal()
    finally:
        pending.abort()
    cb = store.get_columns(ref)
    assert is_device_batch(cb)
    assert cb.layout == descriptor
    assert device_batch_rows(cb) == 3 * 8
    # Per-batch views: contiguous staging block + logical columns.
    rows = 0
    for pb in iter_packed_batches(cb):
        assert pb.packed is not None and pb.packed.flags.c_contiguous
        assert pb.num_rows == 8
        for name in descriptor["columns"]:
            np.testing.assert_array_equal(
                pb[name], logical[name][rows : rows + 8]
            )
        rows += 8
    # Whole-segment logical view (the audit path).
    cols = logical_columns(cb)
    for name in descriptor["columns"]:
        np.testing.assert_array_equal(cols[name], logical[name])
    store.free(ref)


def test_layout_roundtrip_wire_formats(tmp_path):
    """serialize_columns(layout=...) and the vectored scatter-gather
    serializer produce byte-identical output that map_segment_file reads
    back with the descriptor intact — the striped zero-copy TCP plane
    ships stripes of exactly these bytes, so byte identity here IS the
    wire-format layout proof."""
    mat, logical, descriptor = _packed_segment(seed=7)
    cols = {PACKED_COLUMN: mat}
    blob = serialize_columns(cols, layout=descriptor)
    total, bufs = serialize_columns_vectored(cols, layout=descriptor)
    joined = b"".join(bytes(b) for b in bufs)
    assert total == len(blob)
    assert joined == blob  # stripe-served bytes == legacy bytes
    path = tmp_path / "seg"
    path.write_bytes(blob)
    cb = map_segment_file(str(path))
    assert cb.layout == descriptor
    for name in descriptor["columns"]:
        np.testing.assert_array_equal(
            logical_columns(cb)[name], logical[name]
        )


# ---------------------------------------------------------------------------
# Batch-grid alignment math (_PackedOutput)
# ---------------------------------------------------------------------------


def test_packed_output_alignment_and_chunks(local_runtime):
    """head/body/tail partition the reducer interval against the rank
    stream's batch grid for arbitrary (start, total, B); chunk views
    cover [0, total) exactly once in order."""
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.shuffle import _packed_output

    store = runtime.get_context().store
    rng = np.random.default_rng(3)
    for start, total, B in [
        (0, 64, 8), (3, 64, 8), (5, 9, 8), (7, 23, 8), (16, 40, 8),
        (1, 255, 16),
    ]:
        template = {
            "a": np.zeros(1, np.int32), LABEL: np.zeros(1, np.float32)
        }
        layout = {"batch": B, "columns": ["a", LABEL]}
        out = _packed_output(store, (start, layout), total, template)
        h = min(total, (-start) % B)
        m = (total - h) // B
        if m < 1:
            assert out is None  # remainder-only: legacy columnar path
            continue
        t = total - h - m * B
        assert (out.h, out.m, out.t) == (h, m, t)
        # Chunks tile [0, total) in order; write through the views and
        # verify every logical row landed where its stream position says.
        src = rng.integers(0, 1 << 20, total).astype(np.int32)
        pos = 0
        for lo, hi, views in out.chunks():
            assert lo == pos
            for name in ("a", LABEL):
                v = views[name]
                assert v.flags.writeable and len(v) == hi - lo
            views["a"][...] = src[lo:hi]
            views[LABEL][...] = src[lo:hi].astype(np.float32)
            pos = hi
        assert pos == total
        refs = out.seal()
        got_a, got_l = [], []
        for ref in refs:
            cb = store.get_columns(ref)
            cols = logical_columns(cb)
            got_a.append(np.asarray(cols["a"]))
            got_l.append(np.asarray(cols[LABEL]))
            del cb
        np.testing.assert_array_equal(np.concatenate(got_a), src)
        np.testing.assert_array_equal(
            np.concatenate(got_l), src.astype(np.float32)
        )
        out.abort()
        store.free(refs)


def test_packed_output_scatter_matches_chunks(local_runtime):
    """The overlapped-reduce scatter path (windowed, permuted
    destinations) produces exactly the same segments as the fused chunk
    path."""
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.shuffle import _packed_output

    store = runtime.get_context().store
    rng = np.random.default_rng(11)
    start, total, B = 5, 100, 16
    layout = {"batch": B, "columns": ["a", LABEL]}
    template = {"a": np.zeros(1, np.int32), LABEL: np.zeros(1, np.float32)}
    src_a = rng.integers(0, 1 << 20, total).astype(np.int32)
    src_l = rng.random(total).astype(np.float32)

    out = _packed_output(store, (start, layout), total, template)
    perm = rng.permutation(total)
    inv = np.empty(total, np.int64)
    inv[perm] = np.arange(total)
    # Feed in three source-row windows like the overlapped reduce does:
    # window rows [lo, hi) of the concat land at output rows inv[lo:hi].
    for lo, hi in [(0, 37), (37, 70), (70, total)]:
        window = {"a": src_a[lo:hi], LABEL: src_l[lo:hi]}
        out.scatter(inv[lo:hi], window)
    refs = out.seal()
    got_a = np.concatenate(
        [
            np.asarray(logical_columns(store.get_columns(r))["a"])
            for r in refs
        ]
    )
    # out[j] = concat[perm[j]] is the reduce contract; scatter used the
    # inverse so got must equal src permuted.
    np.testing.assert_array_equal(got_a, src_a[perm])
    out.abort()
    store.free(refs)


# ---------------------------------------------------------------------------
# End-to-end: bit-identity, partial tails, engagement
# ---------------------------------------------------------------------------


def _collect_stream(jax_files, queue_name, epochs=2, batch_size=512,
                    drop_last=True, feature_columns=("key",)):
    from ray_shuffling_data_loader_tpu.jax_dataset import (
        JaxShufflingDataset,
    )

    ds = JaxShufflingDataset(
        list(jax_files),
        num_epochs=epochs,
        num_trainers=1,
        batch_size=batch_size,
        rank=0,
        feature_columns=list(feature_columns),
        label_column=LABEL,
        num_reducers=3,
        seed=9,
        drop_last=drop_last,
        queue_name=queue_name,
    )
    out = []
    for epoch in range(epochs):
        ds.set_epoch(epoch)
        for features, label in ds:
            out.append(
                (
                    {k: np.asarray(v) for k, v in features.items()},
                    np.asarray(label),
                )
            )
    return out, ds.stats.as_dict()


@pytest.fixture(scope="module")
def dd_files(local_runtime, tmp_path_factory):
    from ray_shuffling_data_loader_tpu.data_generation import generate_data

    data_dir = tmp_path_factory.mktemp("dd-data")
    filenames, _ = generate_data(
        num_rows=4096,
        num_files=2,
        num_row_groups_per_file=1,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )
    return filenames


def test_stream_bit_identical_layout_on_vs_off(
    local_runtime, dd_files, monkeypatch
):
    """The acceptance-criteria core: every delivered tensor is
    bit-identical with device-direct on vs off (same seed), and the
    direct path demonstrably engaged on the 'on' run."""
    monkeypatch.setenv("RSDL_DEVICE_DIRECT", "off")
    off_stream, off_stats = _collect_stream(dd_files, "q-dd-off")
    monkeypatch.setenv("RSDL_DEVICE_DIRECT", "auto")
    on_stream, on_stats = _collect_stream(dd_files, "q-dd-on")

    assert off_stats["batches_staged_direct"] == 0
    assert on_stats["batches_staged_direct"] > 0, (
        "device-direct never engaged"
    )
    # The direct batches took no host staging copy.
    assert on_stats["bytes_staged"] < off_stats["bytes_staged"]
    assert on_stats["bytes_staged_direct"] > 0

    assert len(on_stream) == len(off_stream)
    for (f_on, l_on), (f_off, l_off) in zip(on_stream, off_stream):
        assert set(f_on) == set(f_off)
        for k in f_on:
            np.testing.assert_array_equal(f_on[k], f_off[k])
            assert f_on[k].dtype == f_off[k].dtype
        np.testing.assert_array_equal(l_on, l_off)


def test_partial_final_batch_layout_on(local_runtime, dd_files, monkeypatch):
    """drop_last=False with the layout on: the ragged tail rides the
    remainder (columnar) path and every key still arrives exactly once."""
    monkeypatch.setenv("RSDL_DEVICE_DIRECT", "auto")
    stream, stats = _collect_stream(
        dd_files, "q-dd-tail", epochs=1, batch_size=1000, drop_last=False
    )
    keys = np.concatenate([f["key"] for f, _ in stream])
    assert sorted(keys.tolist()) == list(range(4096))
    assert stats["batches_staged_direct"] > 0


def test_spec_subset_still_engages(local_runtime, dd_files, monkeypatch):
    """A spec that selects only SOME dataset columns still gets the
    direct path: the reducer packs the requested prefix first and the
    extra columns after it (the stream keeps the full column set, so
    remainders concat with legacy segments and audits stay whole); the
    device_put ships only the prefix. Exactly-once proven on the label
    stream."""
    monkeypatch.setenv("RSDL_DEVICE_DIRECT", "auto")
    from ray_shuffling_data_loader_tpu.jax_dataset import (
        JaxShufflingDataset,
    )

    ds = JaxShufflingDataset(
        list(dd_files),
        num_epochs=1,
        num_trainers=1,
        batch_size=512,
        rank=0,
        feature_columns=["embeddings_name0"],
        label_column="key",
        num_reducers=3,
        seed=4,
        drop_last=False,
        queue_name="q-dd-subset",
    )
    ds.set_epoch(0)
    keys = []
    for features, label in ds:
        assert set(features) == {"embeddings_name0"}
        keys.extend(np.asarray(label).tolist())
    assert sorted(keys) == list(range(4096))
    assert ds.stats.as_dict()["batches_staged_direct"] > 0


def test_shuffle_reduce_overlapped_packed_matches_fused(
    local_runtime, monkeypatch
):
    """The overlapped reduce (RSDL_REDUCE_FETCH_OVERLAP=on) with packing
    engaged produces segment-for-segment identical output to the fused
    path — head/body/tail refs, layout descriptors, and bytes."""
    from ray_shuffling_data_loader_tpu import runtime as rt
    from ray_shuffling_data_loader_tpu.shuffle import shuffle_reduce

    store = rt.get_context().store
    rng = np.random.default_rng(5)
    part_refs = []
    for n in (400, 300, 500):
        pending = store.create_columns(
            {
                "key": ((n,), np.dtype(np.int32)),
                LABEL: ((n,), np.dtype(np.float32)),
            }
        )
        try:
            pending.columns["key"][...] = rng.integers(
                0, 1 << 20, n
            ).astype(np.int32)
            pending.columns[LABEL][...] = rng.random(n).astype(np.float32)
            # publish_slices → refs carry row windows, which is what the
            # driver's count derivation (and the overlap gate) needs.
            part_refs.append(pending.publish_slices([(0, n)])[0])
        finally:
            pending.abort()
    pack = (7, {"batch": 64, "columns": ["key", LABEL]})

    def _logical_stream(refs):
        keys, labels, layouts = [], [], []
        for ref in refs:
            cb = store.get_columns(ref)
            cols = logical_columns(cb)
            keys.append(np.asarray(cols["key"]))
            labels.append(np.asarray(cols[LABEL]))
            layouts.append(cb.layout)
            del cb
        return np.concatenate(keys), np.concatenate(labels), layouts

    monkeypatch.setenv("RSDL_REDUCE_FETCH_OVERLAP", "off")
    fused = shuffle_reduce(1, epoch=0, seed=2, part_refs=part_refs,
                           pack=pack)
    monkeypatch.setenv("RSDL_REDUCE_FETCH_OVERLAP", "on")
    overlapped = shuffle_reduce(1, epoch=0, seed=2, part_refs=part_refs,
                                pack=pack)
    assert isinstance(fused, list) and len(fused) == 3  # head/body/tail
    assert isinstance(overlapped, list) and len(overlapped) == len(fused)
    fk, fl, f_lay = _logical_stream(fused)
    ok, ol, o_lay = _logical_stream(overlapped)
    np.testing.assert_array_equal(fk, ok)
    np.testing.assert_array_equal(fl, ol)
    assert f_lay == o_lay
    assert any(
        lay and lay.get("kind") == DEVICE_BATCH_KIND for lay in f_lay
    )
    store.free(fused)
    store.free(overlapped)
    store.free(part_refs)


def test_take_multi_in_kernel_bounds(local_runtime):
    """ISSUE 8 satellite: rsdl_take_multi bounds-checks in the kernel —
    an out-of-range index raises IndexError with no Python pre-scan, a
    negative index falls back to numpy wraparound semantics, and the
    in-bounds gather is exact."""
    from ray_shuffling_data_loader_tpu import native

    if not native.native_available():
        pytest.skip("native kernels unavailable")
    parts = [
        np.arange(10, dtype=np.int32),
        np.arange(10, 25, dtype=np.int32),
    ]
    concat = np.concatenate(parts)
    idx = np.array([0, 24, 7, 13], dtype=np.int64)
    np.testing.assert_array_equal(
        native.take_multi(parts, idx, n_threads=4), concat[idx]
    )
    with pytest.raises(IndexError):
        native.take_multi(
            parts, np.array([0, 25], dtype=np.int64), n_threads=4
        )
    np.testing.assert_array_equal(
        native.take_multi(
            parts, np.array([-1, 3], dtype=np.int64), n_threads=4
        ),
        concat[[-1, 3]],
    )
