"""Online critical-path tests (ISSUE 9): the shared interval math
(merge/sole-active sweep/critical-path tie-break), the live analyzer
over task records, the rsdl_critical_* gauges — and the PARITY proof:
the live ``telemetry/critical.py`` verdict and the post-hoc
``tools/epoch_report.py`` verdict must be identical on the same
fixture intervals, because they are (by construction) the same code."""

import importlib.util
import os

import pytest

from ray_shuffling_data_loader_tpu.telemetry import critical, metrics

_ENV = ("RSDL_METRICS", "RSDL_METRICS_DIR", "RSDL_OBS_PORT")


@pytest.fixture
def crit_env(tmp_path):
    saved = {k: os.environ.get(k) for k in _ENV}
    os.environ["RSDL_METRICS"] = "1"
    os.environ["RSDL_METRICS_DIR"] = str(tmp_path / "metrics-spool")
    os.environ.pop("RSDL_OBS_PORT", None)
    metrics.refresh_from_env()
    metrics.reset()
    yield
    metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    metrics.refresh_from_env()


@pytest.fixture(scope="module")
def epoch_report():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "epoch_report_parity",
        os.path.join(repo, "tools", "epoch_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_merge_and_totals():
    merged = critical.merge_intervals([(3.0, 5.0), (1.0, 2.0), (4.0, 7.0)])
    assert merged == [(1.0, 2.0), (3.0, 7.0)]
    assert critical.intervals_total(merged) == pytest.approx(5.0)


def test_profile_epoch_sole_shares_and_tiebreak():
    # map [0, 10], reduce [4, 10]: map sole 4s, overlap 6s, reduce
    # sole 0 — map is the critical path.
    row = critical.profile_epoch(
        {"map": [(0.0, 10.0)], "reduce": [(4.0, 10.0)]}
    )
    assert row["critical_path"] == "map"
    assert row["map_sole_s"] == pytest.approx(4.0)
    assert row["overlap_s"] == pytest.approx(6.0)
    assert row["sole_share"]["map"] == pytest.approx(0.4)
    # A perfect tie breaks toward the LATER pipeline stage.
    row = critical.profile_epoch(
        {"map": [(0.0, 1.0)], "reduce": [(2.0, 3.0)]}
    )
    assert row["critical_path"] == "reduce"
    assert row["idle_s"] == pytest.approx(1.0)


def test_intervals_from_task_records_and_analyze():
    records = [
        {"ts": 10.0, "dur_s": 8.0, "stage": "map", "epoch": 0},
        {"ts": 11.0, "dur_s": 1.0, "stage": "reduce", "epoch": 0},
        {"ts": 20.0, "dur_s": 1.0, "stage": "map", "epoch": 1},
        {"ts": 30.0, "dur_s": 9.0, "stage": "reduce", "epoch": 1},
        {"ts": 99.0, "dur_s": 1.0, "stage": "map"},  # no epoch: skipped
    ]
    analysis = critical.analyze(records=records, now=31.0)
    rows = {r["epoch"]: r for r in analysis["epochs"]}
    assert rows[0]["critical_path"] == "map"
    assert rows[1]["critical_path"] == "reduce"
    # No in-flight window registered: current = the latest epoch seen.
    assert analysis["current"]["epoch"] == 1
    assert analysis["current"]["critical_path"] == "reduce"
    assert analysis["run_critical_path"] == "reduce"
    assert analysis["tasks_total"] == 5


def test_publish_metrics_gauges_one_hot_and_zeroing(crit_env):
    records = [
        {"ts": 10.0, "dur_s": 8.0, "stage": "map", "epoch": 0},
        {"ts": 11.0, "dur_s": 1.0, "stage": "reduce", "epoch": 0},
    ]
    critical.publish_metrics(critical.analyze(records=records, now=12.0))
    snap = metrics.registry.snapshot()
    assert snap["critical.epoch"] == 0.0
    assert snap["critical.path{stage=map}"] == 1.0
    assert snap["critical.path{stage=reduce}"] == 0.0
    assert snap["critical.sole_share{stage=map}"] > 0.5
    # The next epoch has no reduce tasks: its stale gauges must zero.
    records2 = [{"ts": 20.0, "dur_s": 2.0, "stage": "plan", "epoch": 1}]
    critical.publish_metrics(
        critical.analyze(records=records2, now=22.0)
    )
    snap = metrics.registry.snapshot()
    assert snap["critical.path{stage=map}"] == 0.0
    assert snap["critical.sole_share{stage=map}"] == 0.0
    assert snap["critical.path{stage=plan}"] == 1.0


# ---------------------------------------------------------------------------
# Online vs post-hoc parity (ISSUE 9 acceptance)
# ---------------------------------------------------------------------------

# One fixture, two encodings: the same per-(epoch, stage) busy windows
# expressed as worker task records (the live analyzer's input) and as
# Chrome-trace spans (the report's input). Seconds offsets; the trace
# side scales to microseconds. Shapes chosen to exercise overlap,
# containment, idle gaps, and a different winner per epoch.
_FIXTURE = {
    0: {"map": [(0.0, 6.0), (2.0, 8.0)], "reduce": [(5.0, 9.0)]},
    1: {"map": [(0.0, 2.0)], "reduce": [(1.0, 9.5), (3.0, 4.0)]},
    2: {"map": [(0.0, 4.0)], "reduce": [(0.0, 4.0)]},  # exact tie
}


def _as_task_records():
    out = []
    for epoch, stages in _FIXTURE.items():
        for stage, ivs in stages.items():
            for start, end in ivs:
                out.append(
                    {
                        "ts": end,
                        "dur_s": end - start,
                        "stage": stage,
                        "epoch": epoch,
                        "host": "h",
                        "pid": 1,
                    }
                )
    return out


def _as_trace_spans():
    out = []
    for epoch, stages in _FIXTURE.items():
        for stage, ivs in stages.items():
            for start, end in ivs:
                out.append(
                    {
                        "name": stage,
                        "ph": "X",
                        "ts": start * 1e6,
                        "dur": (end - start) * 1e6,
                        "pid": 1,
                        "tid": 1,
                        "args": {"epoch": epoch},
                    }
                )
    return out


def test_online_matches_posthoc_verdicts(epoch_report):
    """The acceptance bar: identical critical-path verdicts (and the
    underlying busy/sole/overlap numbers) from the live analyzer and
    the post-hoc report on the same fixture intervals."""
    live = {
        r["epoch"]: r
        for r in critical.analyze(
            records=_as_task_records(), now=100.0
        )["epochs"]
    }
    posthoc = epoch_report.collect_epochs(_as_trace_spans())
    assert set(live) == set(posthoc) == set(_FIXTURE)
    for epoch in _FIXTURE:
        lrow, prow = live[epoch], posthoc[epoch]
        assert lrow["critical_path"] == prow["critical_path"], epoch
        for key in ("wall_s", "idle_s", "overlap_s", "map_s",
                    "map_sole_s", "reduce_s", "reduce_sole_s"):
            assert lrow[key] == pytest.approx(prow[key], abs=1e-6), (
                epoch, key,
            )
    # And the run-level verdict agrees too.
    report = epoch_report.build_report(
        _as_trace_spans(), [], [], None, None, 10.0, 10.0
    )
    live_run = critical.analyze(
        records=_as_task_records(), now=100.0
    )["run_critical_path"]
    assert report["header"]["critical_path"] == live_run


def test_parity_tiebreak_is_shared():
    """The exact-tie epoch names the later stage in BOTH views — the
    tie-break rule cannot drift because it is one function."""
    row_live = critical.profile_epoch(_FIXTURE[2])
    row_posthoc = critical.profile_epoch(
        {
            s: [(a * 1e6, b * 1e6) for a, b in ivs]
            for s, ivs in _FIXTURE[2].items()
        },
        scale=1e6,
    )
    assert (
        row_live["critical_path"]
        == row_posthoc["critical_path"]
        == "reduce"
    )
