"""Device-resident shuffle tests: exactly-once per epoch, determinism,
rank splits, drop_last, skip_batches resume, sharded gather — all on the
8-virtual-device CPU mesh.

The resident path replaces the host map/reduce per epoch with an
on-device permutation + gather (see ``resident.py``); these tests pin the
same shuffle contract the reference engine provides (reference
``shuffle.py:171-200``, ``dataset.py:108-188``), which the reference
itself never tested for the real shuffle path (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_shuffling_data_loader_tpu.data_generation import (
    EMBEDDING_COLUMNS,
    LABEL_COLUMN,
)
from ray_shuffling_data_loader_tpu.parallel import DATA_AXIS, make_mesh
from ray_shuffling_data_loader_tpu.resident import (
    DeviceResidentShufflingDataset,
    dataset_num_rows,
    fits_device,
    packed_nbytes,
)

NUM_ROWS = 8192
FEATURES = EMBEDDING_COLUMNS[:3] + ["key"]


@pytest.fixture(scope="module")
def resident_files(local_runtime, tmp_path_factory):
    from ray_shuffling_data_loader_tpu.data_generation import generate_data

    data_dir = tmp_path_factory.mktemp("resident-data")
    filenames, _ = generate_data(
        num_rows=NUM_ROWS,
        num_files=3,  # deliberately not a divisor of the row count
        num_row_groups_per_file=2,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )
    return filenames


def _make(files, **kw):
    kw.setdefault("num_epochs", 3)
    kw.setdefault("batch_size", 512)
    kw.setdefault("feature_columns", FEATURES)
    kw.setdefault("label_column", LABEL_COLUMN)
    kw.setdefault("mesh", make_mesh(model_parallelism=1))
    kw.setdefault("seed", 7)
    # Exercise the piece-streaming loop: several pieces per file and a
    # ragged final piece.
    kw.setdefault("piece_rows", 1000)
    return DeviceResidentShufflingDataset(files, **kw)


def test_exactly_once_and_sharded(local_runtime, resident_files):
    ds = _make(resident_files)
    assert ds.num_rows == NUM_ROWS
    orders = []
    for epoch in range(2):
        ds.set_epoch(epoch)
        seen = []
        for features, label in ds:
            assert set(features) == set(FEATURES)
            arr = features["key"]
            assert isinstance(arr, jax.Array)
            assert arr.dtype == jnp.int32
            assert arr.shape == (512,)
            assert arr.sharding.spec == (DATA_AXIS,)
            assert label.dtype == jnp.float32
            assert float(jnp.min(label)) >= 0.0
            assert float(jnp.max(label)) <= 1.0
            seen.append(np.asarray(arr))
        flat = np.concatenate(seen)
        # 8192 rows / 512 = 16 exact batches: every row exactly once.
        assert len(flat) == NUM_ROWS
        assert np.array_equal(np.sort(flat), np.arange(NUM_ROWS))
        orders.append(flat)
    # Epochs shuffle differently.
    assert not np.array_equal(orders[0], orders[1])


def test_label_values_roundtrip(local_runtime, resident_files):
    """The bitcast unpack must reproduce the decoded float values, not
    just their set membership: compare against a direct Parquet read."""
    import pyarrow.parquet as pq

    expected = {}
    for f in resident_files:
        t = pq.read_table(f, columns=["key", LABEL_COLUMN])
        keys = t.column("key").to_numpy()
        vals = t.column(LABEL_COLUMN).to_numpy().astype(np.float32)
        expected.update(zip(keys.tolist(), vals.tolist()))
    ds = _make(resident_files)
    ds.set_epoch(0)
    for features, label in ds:
        keys = np.asarray(features["key"])
        vals = np.asarray(label)
        for k, v in zip(keys.tolist(), vals.tolist()):
            assert expected[k] == pytest.approx(v)
        break  # one batch is plenty at this cost


def test_deterministic_given_seed(local_runtime, resident_files):
    a = _make(resident_files)
    b = _make(resident_files)
    a.set_epoch(1)
    b.set_epoch(1)
    fa, la = next(iter(a))
    fb, lb = next(iter(b))
    assert np.array_equal(np.asarray(fa["key"]), np.asarray(fb["key"]))
    assert np.array_equal(np.asarray(la), np.asarray(lb))


def test_rank_split_disjoint_and_complete(local_runtime, resident_files):
    ranks = [
        _make(resident_files, num_trainers=2, rank=r, drop_last=False)
        for r in range(2)
    ]
    all_keys = []
    for ds in ranks:
        ds.set_epoch(0)
        rank_keys = np.concatenate(
            [np.asarray(f["key"]) for f, _ in ds]
        )
        all_keys.append(rank_keys)
    assert not set(all_keys[0].tolist()) & set(all_keys[1].tolist())
    union = np.concatenate(all_keys)
    assert np.array_equal(np.sort(union), np.arange(NUM_ROWS))


def test_drop_last_and_ragged_tail(local_runtime, resident_files):
    # 8192 rows at batch 480: 17 full batches + 32-row tail.
    ds = _make(resident_files, batch_size=480, drop_last=True)
    ds.set_epoch(0)
    batches = [np.asarray(f["key"]) for f, _ in ds]
    assert len(batches) == NUM_ROWS // 480
    assert all(len(b) == 480 for b in batches)

    ds2 = _make(resident_files, batch_size=480, drop_last=False)
    assert ds2.num_batches == NUM_ROWS // 480 + 1
    ds2.set_epoch(0)
    batches = [np.asarray(f["key"]) for f, _ in ds2]
    assert len(batches[-1]) == NUM_ROWS % 480
    flat = np.concatenate(batches)
    assert np.array_equal(np.sort(flat), np.arange(NUM_ROWS))


def test_skip_batches_resume(local_runtime, resident_files):
    ds = _make(resident_files)
    ds.set_epoch(2)
    full = [np.asarray(f["key"]) for f, _ in ds]
    ds.set_epoch(2, skip_batches=5)
    resumed = [np.asarray(f["key"]) for f, _ in ds]
    assert len(resumed) == len(full) - 5
    for a, b in zip(full[5:], resumed):
        assert np.array_equal(a, b)


def test_materialized_and_gather_paths_identical(local_runtime, resident_files):
    """materialize_epoch changes the schedule (one whole-epoch gather vs
    per-batch gathers), never the stream: same seed -> same batches, so
    checkpoints resume exactly across the setting."""
    mat = _make(resident_files, materialize_epoch=True)
    gat = _make(resident_files, materialize_epoch=False)
    assert mat._materialize is True and gat._materialize is False
    for epoch in (0, 1):
        mat.set_epoch(epoch)
        gat.set_epoch(epoch)
        for (fa, la), (fb, lb) in zip(mat, gat):
            assert np.array_equal(np.asarray(fa["key"]), np.asarray(fb["key"]))
            assert np.array_equal(np.asarray(la), np.asarray(lb))


def test_epoch_bounds_and_bad_rank(local_runtime, resident_files):
    ds = _make(resident_files)
    with pytest.raises(ValueError):
        ds.set_epoch(99)
    with pytest.raises(RuntimeError):
        next(iter(_make(resident_files)))
    with pytest.raises(ValueError):
        _make(resident_files, num_trainers=2, rank=2)


def test_close_releases_and_blocks_iteration(local_runtime, resident_files):
    ds = _make(resident_files)
    ds.set_epoch(0)
    next(iter(ds))
    ds.close()
    assert ds._buf is None
    with pytest.raises(RuntimeError, match="closed"):
        next(iter(ds))
    with pytest.raises(RuntimeError, match="closed"):
        ds.set_epoch(0)


def test_close_invalidates_live_iterator(local_runtime, resident_files):
    ds = _make(resident_files, lookahead=1)
    ds.set_epoch(0)
    it = iter(ds)
    next(it)
    ds.close()
    with pytest.raises(RuntimeError, match="closed"):
        # Drain: the lookahead may hold a couple of pre-dispatched
        # batches, but the next dispatch must fail fast.
        for _ in range(5):
            next(it)


def test_stats_accounting(local_runtime, resident_files):
    ds = _make(resident_files)
    # Features + label, 4 bytes per value, every real row staged once.
    assert ds.stats.bytes_staged == packed_nbytes(NUM_ROWS, len(FEATURES))
    ds.set_epoch(0)
    n = sum(1 for _ in ds)
    assert ds.stats.batches_staged == n


def test_range_decode(local_runtime, resident_files):
    """Row-group-granular range decode (pod staging's per-file slice):
    exact rows, within one group and across the group boundary."""
    from ray_shuffling_data_loader_tpu import runtime as rt
    from ray_shuffling_data_loader_tpu.resident import (
        _decode_narrow_range_to_store,
    )

    store = rt.get_context().store
    # resident_files[0] holds keys [0, ~2731) in 2 row groups.
    for lo, hi in ((100, 900), (1000, 2400)):
        ref = _decode_narrow_range_to_store(
            resident_files[0], ["key"], lo, hi
        )
        keys = np.asarray(store.get_columns(ref)["key"])
        assert np.array_equal(keys, np.arange(lo, hi))
        store.free([ref])
    with pytest.raises(ValueError, match="outside"):
        _decode_narrow_range_to_store(resident_files[0], ["key"], 10**9, 10**9 + 1)
    # Partially-overlapping ranges must raise too, never silently truncate.
    with pytest.raises(ValueError, match="outside"):
        _decode_narrow_range_to_store(resident_files[0], ["key"], 2000, 10**9)


def test_num_rows_hint(local_runtime, resident_files):
    ds = _make(resident_files, num_rows=NUM_ROWS)
    assert ds.num_rows == NUM_ROWS
    # A wrong hint must be rejected, not silently mis-index.
    with pytest.raises(ValueError, match="num_rows"):
        _make(resident_files, num_rows=NUM_ROWS - 1)


def test_fits_device_policy(local_runtime, resident_files, monkeypatch):
    assert dataset_num_rows(resident_files) == NUM_ROWS
    # Auto never picks resident on the CPU backend (the "device" is host
    # RAM — measured slower than the map/reduce path there) ...
    monkeypatch.delenv("RSDL_RESIDENT_BUDGET_GB", raising=False)
    assert fits_device(resident_files, len(FEATURES)) is False
    # ... unless the operator opts in with an explicit budget.
    monkeypatch.setenv("RSDL_RESIDENT_BUDGET_GB", "1")
    assert fits_device(resident_files, len(FEATURES)) is True
    # An explicit budget the dataset exceeds still says no.
    monkeypatch.setenv("RSDL_RESIDENT_BUDGET_GB", "1e-9")
    assert fits_device(resident_files, len(FEATURES)) is False


@pytest.mark.parametrize("materialize", [True, False])
def test_fused_epoch_matches_per_batch(
    local_runtime, resident_files, materialize
):
    """Epoch-fused training (one jitted lax.scan per epoch) must produce
    the same final state and per-batch losses as driving the identical
    step through the per-batch iterator — on both epoch schedules."""
    from ray_shuffling_data_loader_tpu.resident import make_fused_epoch

    def make_ds():
        return DeviceResidentShufflingDataset(
            list(resident_files),
            num_epochs=2,
            batch_size=1024,
            feature_columns=FEATURES,
            label_column=LABEL_COLUMN,
            seed=41,
            materialize_epoch=materialize,
        )

    def step_body(state, feats, label):
        def loss_fn(w):
            pred = w * feats["key"].astype(jnp.float32) / NUM_ROWS
            return jnp.mean((pred - label) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(state)
        return state - 0.05 * g, {"loss": loss}

    ds_f = make_ds()
    run = make_fused_epoch(ds_f, step_body, donate_state=False)
    state_f = jnp.float32(0.5)
    all_losses = []
    for epoch in range(2):
        state_f, losses = run(state_f, epoch)
        all_losses.append(np.asarray(losses))
    ds_f.close()

    ds_p = make_ds()
    step = jax.jit(step_body)
    state_p = jnp.float32(0.5)
    ref_losses = []
    for epoch in range(2):
        ds_p.set_epoch(epoch)
        ep = []
        for feats, label in ds_p:
            state_p, metrics = step(state_p, feats, label)
            ep.append(float(metrics["loss"]))
        ref_losses.append(np.asarray(ep, np.float32))
    ds_p.close()

    for got, want in zip(all_losses, ref_losses):
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        float(state_f), float(state_p), rtol=1e-6, atol=1e-6
    )


@pytest.mark.parametrize("materialize", [True, False])
def test_fused_epoch_multi_device_matches(
    local_runtime, resident_files, materialize
):
    """The multi-device fused path (scan over the pre-sharded epoch
    tensor — no per-step data collectives) must match the per-batch
    iterator bit-for-bit on an 8-device mesh, on both epoch schedules
    (VERDICT r3 item 3: fusion may not be single-device-only)."""
    from jax.sharding import Mesh

    from ray_shuffling_data_loader_tpu.resident import make_fused_epoch

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    assert mesh.devices.size > 1, "conftest provides 8 virtual devices"

    def make_ds():
        return DeviceResidentShufflingDataset(
            list(resident_files),
            num_epochs=2,
            batch_size=1024,
            feature_columns=FEATURES,
            label_column=LABEL_COLUMN,
            seed=43,
            mesh=mesh,
            materialize_epoch=materialize,
        )

    def step_body(state, feats, label):
        def loss_fn(w):
            pred = w * feats["key"].astype(jnp.float32) / NUM_ROWS
            return jnp.mean((pred - label) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(state)
        return state - 0.05 * g, {"loss": loss}

    ds_f = make_ds()
    run = make_fused_epoch(ds_f, step_body, donate_state=False)
    state_f = jnp.float32(0.5)
    all_losses = []
    for epoch in range(2):
        state_f, losses = run(state_f, epoch)
        all_losses.append(np.asarray(losses))
    ds_f.close()

    ds_p = make_ds()
    step = jax.jit(step_body)
    state_p = jnp.float32(0.5)
    ref_losses = []
    for epoch in range(2):
        ds_p.set_epoch(epoch)
        ep = []
        for feats, label in ds_p:
            state_p, metrics = step(state_p, feats, label)
            ep.append(float(metrics["loss"]))
        ref_losses.append(np.asarray(ep, np.float32))
    ds_p.close()

    for got, want in zip(all_losses, ref_losses):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        float(state_f), float(state_p), rtol=1e-5, atol=1e-6
    )
