"""Fast in-process unit tests for bench.py's measurement-regime logic
(ADVICE r5): `_target_context` override validation with the non-strict
error-JSON fallback, and the tunnel heuristic requiring ACTIVE axon
markers — not mere existence of ~/.axon_site on disk.

Separate from test_bench.py, whose module-wide `slow` mark covers the
subprocess contract runs; everything here is a plain function call.
"""

import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import bench


@pytest.fixture
def clean_env(monkeypatch):
    """No regime override, no axon markers — the heuristic's baseline."""
    monkeypatch.delenv("RSDL_BENCH_TARGET_CONTEXT", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("PJRT_DEVICE", raising=False)
    monkeypatch.delenv("PYTHONPATH", raising=False)


def test_valid_override_wins(clean_env, monkeypatch):
    monkeypatch.setenv("RSDL_BENCH_TARGET_CONTEXT", "direct-tpu")
    assert bench._target_context("cpu") == "direct-tpu"


def test_bad_override_strict_raises(clean_env, monkeypatch):
    monkeypatch.setenv("RSDL_BENCH_TARGET_CONTEXT", "direct-tpuu")
    with pytest.raises(ValueError, match="direct-tpuu"):
        bench._target_context("tpu")


def test_bad_override_nonstrict_falls_back(clean_env, monkeypatch):
    """The error-JSON path must classify heuristically on a typo'd
    override, never raise (a raise there broke the one-JSON-line
    contract)."""
    monkeypatch.setenv("RSDL_BENCH_TARGET_CONTEXT", "direct-tpuu")
    assert bench._target_context("cpu", strict=False) == "cpu-failover"
    result = bench._error_result("cpu", "boom")
    assert result["target_context"] == "cpu-failover"
    assert result["error"] == "boom"


def test_axon_site_dir_alone_is_not_a_tunnel(clean_env, monkeypatch,
                                             tmp_path):
    """ADVICE r5: ~/.axon_site existing on disk must not demote a direct
    TPU capture — only an ACTIVE marker (env/PYTHONPATH) may."""
    home = tmp_path / "home"
    (home / ".axon_site").mkdir(parents=True)
    monkeypatch.setenv("HOME", str(home))
    assert bench._target_context("tpu") == "direct-tpu"


@pytest.mark.parametrize(
    "env",
    [
        {"JAX_PLATFORMS": "axon,cpu"},
        {"PJRT_DEVICE": "axon"},
        {"PYTHONPATH": "/opt/foo:/some/where/.axon_site"},
    ],
)
def test_active_axon_markers_mean_tunnel(clean_env, monkeypatch, env):
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    assert bench._target_context("tpu") == "tunneled-tpu"


def test_stray_axon_substring_is_not_a_marker(clean_env, monkeypatch):
    """Exact tokens/basenames only: 'jaxon'/'saxonpy' paths must not
    demote a direct-TPU capture."""
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setenv("PYTHONPATH", "/opt/jaxon:/usr/lib/saxonpy")
    assert bench._target_context("tpu") == "direct-tpu"
