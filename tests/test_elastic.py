"""Elastic control-plane tests (ISSUE 10): WorkerPool / ClusterScheduler
membership (add, graceful retire, drain-aware dispatch), the graceful
drain protocol (clean handover, crash-mid-drain degrading into the
failover backstop), the tiered evictor (demote shm→spill readable in
place, drop with lineage recovery, the in-flight eviction fence), and
the chaos-lane acceptance proof: a run with an autoscale-up, a drain
with a crash mid-drain, and a shm→spill→drop eviction whose dropped
segments are re-materialized from lineage — audit ok=true throughout
and the capacity ledger's per-tier residency reconciling to zero at
session cleanup. Plus the fresh-interpreter zero-overhead proof for
``RSDL_ELASTIC`` unset.

Function-scoped runtimes per the chaos/obs test convention: fault
schedules and telemetry gates are parsed once per process, so every
test arms its own environment before spawning pools."""

import collections
import os
import signal
import subprocess
import sys
import time
import types

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import runtime, telemetry
from ray_shuffling_data_loader_tpu.runtime import cluster as cluster_mod
from ray_shuffling_data_loader_tpu.runtime import elastic as elastic_mod
from ray_shuffling_data_loader_tpu.runtime import faults
from ray_shuffling_data_loader_tpu.runtime.store import (
    ObjectLostError,
    ObjectStore,
)
from ray_shuffling_data_loader_tpu.runtime.tasks import WorkerPool
from ray_shuffling_data_loader_tpu.telemetry import audit as _audit
from ray_shuffling_data_loader_tpu.telemetry import capacity, events
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics
from ray_shuffling_data_loader_tpu.telemetry import trace

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ENV = (
    "RSDL_METRICS", "RSDL_METRICS_DIR", "RSDL_OBS_PORT", "RSDL_TS",
    "RSDL_ELASTIC", "RSDL_SHM_DIR", "RSDL_SPILL_DIR",
    "RSDL_STORE_CAPACITY_BYTES", "RSDL_EVENTS_DIR",
    "RSDL_AUDIT", "RSDL_AUDIT_STRICT", "RSDL_AUDIT_DIR",
    "RSDL_FAULTS", "RSDL_FAULTS_SEED", "RSDL_DRAIN_DEADLINE_S",
    "RSDL_EVICT_HIGH_WATERMARK", "RSDL_EVICT_LOW_WATERMARK",
    "RSDL_EVICT_COOLDOWN_S", "RSDL_EVICT_DROP_AGE_S",
    "RSDL_ELASTIC_MAX_WORKERS",
)


@pytest.fixture
def elastic_env(tmp_path):
    """Metrics on (the control loop's input planes), ledger/event state
    reset, cluster membership globals cleared — function-scoped."""
    saved = {k: os.environ.get(k) for k in _ENV}
    os.environ["RSDL_METRICS"] = "1"
    os.environ["RSDL_METRICS_DIR"] = str(tmp_path / "metrics-spool")
    for k in _ENV[2:]:
        # The CI elastic lane arms an ambient low-prob RSDL_FAULTS
        # schedule; let it ride (tests that need determinism arm their
        # own) — everything else starts from a clean slate.
        if k not in ("RSDL_FAULTS", "RSDL_FAULTS_SEED"):
            os.environ.pop(k, None)
    _metrics.refresh_from_env()
    _metrics.reset()
    capacity.reset(clear_spool=True)
    events.reset()
    cluster_mod.reset_membership()
    faults.refresh_from_env()
    yield tmp_path
    elastic_mod.stop()
    runtime.shutdown()
    cluster_mod.reset_membership()
    capacity.reset(clear_spool=True)
    events.reset()
    _audit.reset()
    _metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    _metrics.refresh_from_env()
    _audit.refresh_from_env()
    faults.refresh_from_env()


def _events_of(kind):
    return [r for r in events.load() if r.get("kind") == kind]


def _bare_ctx(store, scheduler=None):
    """A minimal context for driving a controller without a runtime
    session (the controller only touches .store/.scheduler/.cluster/
    .session)."""
    return types.SimpleNamespace(
        store=store,
        scheduler=scheduler
        if scheduler is not None
        else types.SimpleNamespace(width=1),
        cluster=None,
        session=store.session,
        runtime_dir=None,
    )


# ---------------------------------------------------------------------------
# Gating
# ---------------------------------------------------------------------------


def test_mode_parsing(monkeypatch):
    for raw, want in (
        ("", False), ("off", False), ("0", False), ("false", False),
        ("auto", True), ("on", True), ("1", True),
    ):
        monkeypatch.setenv("RSDL_ELASTIC", raw)
        assert elastic_mod.enabled() is want, raw


def test_maybe_start_requires_metrics(monkeypatch):
    monkeypatch.setenv("RSDL_ELASTIC", "auto")
    monkeypatch.delenv("RSDL_METRICS", raising=False)
    _metrics.refresh_from_env()
    try:
        assert elastic_mod.maybe_start() is False
        assert not elastic_mod.running()
    finally:
        _metrics.refresh_from_env()


# ---------------------------------------------------------------------------
# WorkerPool membership (the single-host actuators)
# ---------------------------------------------------------------------------


def _square(x):
    return x * x


def _napping_square(x, delay):
    time.sleep(delay)
    return x * x


def test_pool_add_and_graceful_retire(elastic_env):
    pool = WorkerPool(1)
    try:
        assert pool.submit(_square, 3).result(timeout=30) == 9
        assert pool.add_workers(1) == 2
        assert pool.num_workers == 2 and pool.width == 2
        # In-flight work finishes across the membership change; the
        # retire pill is drain-aware: the retiring worker completes its
        # current task, takes no more, exits cleanly — no future fails.
        futs = [
            pool.submit(_napping_square, i, 0.2) for i in range(4)
        ]
        retired = pool.retire_workers(1, deadline_s=30.0)
        assert len(retired) == 1
        assert [f.result(timeout=30) for f in futs] == [0, 1, 4, 9]
        assert pool.num_workers == 1
        # Still functional after the retire.
        assert pool.submit(_square, 5).result(timeout=30) == 25
        # Never below one worker.
        assert pool.retire_workers(5, deadline_s=5.0) == []
        assert pool.num_workers == 1
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# ClusterScheduler membership + drain-aware dispatch
# ---------------------------------------------------------------------------


class FakeAgent:
    def __init__(self, name, alive=True):
        self.address = ("tcp", name, 1)
        self.alive = alive
        self.calls = 0

    def call(self, method, *args):
        self.calls += 1
        return "ok"

    def ping(self, timeout=None):
        return self.alive


def test_scheduler_add_retire_remove_membership(elastic_env):
    a, b = FakeAgent("a"), FakeAgent("b")
    sched = cluster_mod.ClusterScheduler([a])
    try:
        assert sched.add_agent(b, num_workers=2)
        assert not sched.add_agent(b)  # idempotent by address
        assert sched.agent_addresses == {a.address, b.address}
        assert sched.width == 3
        # Draining agents take no new placements...
        sched.retire_agent(b)
        picks = {sched._next_agent().address for _ in range(8)}
        assert picks == {a.address}
        # ... unless every agent is draining (degrade, never hang).
        sched.retire_agent(a)
        assert sched._next_agent() is not None
        sched.add_agent(b)  # re-admission clears the drain mark
        picks = {sched._next_agent().address for _ in range(8)}
        assert b.address in picks
        section = cluster_mod.membership_section()
        rows = {r["address"]: r for r in section["agents"]}
        assert rows["tcp:a:1"]["draining"] is True
        assert rows["tcp:b:1"]["draining"] is False
        # Planned removal records the retirement (no eviction counter).
        assert sched.remove_agent(a)
        section = cluster_mod.membership_section()
        assert section["retired"] == ["tcp:a:1"]
        assert sched.agent_addresses == {b.address}
    finally:
        sched.shutdown()


def test_drain_host_clean_handover(elastic_env):
    a, b = FakeAgent("a"), FakeAgent("b")
    sched = cluster_mod.ClusterScheduler([a, b])
    store = ObjectStore("drainsess")
    ctl = elastic_mod.ElasticController(_bare_ctx(store, sched))
    try:
        outcome = ctl.drain_host(b, deadline_s=5.0)
        assert outcome == "drained"
        assert sched.agent_addresses == {a.address}
        assert ctl.drains == 1
        assert _events_of("scale.drain")
        assert _events_of("scale.drain_done")
        assert not _events_of("scale.drain_backstop")
        section = cluster_mod.membership_section()
        assert "tcp:b:1" in section["retired"]
        # The drain-age gauge is back to zero after completion.
        snap = _metrics.registry.snapshot()
        assert snap.get("elastic.drain_age_seconds") == 0.0
    finally:
        sched.shutdown()


def test_drain_backstop_on_crash_mid_drain(elastic_env):
    """An agent that dies while its in-flight window is being waited
    out must degrade into the fault plane's failover (_drop_agent +
    agent.evicted), never hang the drain."""
    a, b = FakeAgent("a"), FakeAgent("b", alive=False)
    sched = cluster_mod.ClusterScheduler([a, b])
    store = ObjectStore("drainsess2")
    ctl = elastic_mod.ElasticController(_bare_ctx(store, sched))
    evicted = []
    sched.on_agent_dead = evicted.append
    try:
        # One task "in flight" on the victim when it crashes.
        sched._inflight_adjust(b.address, +1)
        start = time.monotonic()
        outcome = ctl.drain_host(b, deadline_s=30.0)
        # The ping detected the crash immediately — no deadline wait.
        assert time.monotonic() - start < 10.0
        assert outcome == "backstop"
        assert sched.agent_addresses == {a.address}
        assert evicted and evicted[0] is b
        assert _events_of("scale.drain_backstop")
        assert _events_of("agent.evicted")
        snap = _metrics.registry.snapshot()
        assert snap.get("recovery.agent_evictions") == 1.0
        assert snap.get("elastic.drain_backstops_total") == 1.0
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# Tiered evictor
# ---------------------------------------------------------------------------


def _evict_store(tmp_path, budget=None):
    os.environ["RSDL_SHM_DIR"] = str(tmp_path / "shm")
    os.environ["RSDL_SPILL_DIR"] = str(tmp_path / "spill")
    if budget is not None:
        os.environ["RSDL_STORE_CAPACITY_BYTES"] = str(budget)
    return ObjectStore("evictsess")


def test_evictor_demote_then_drop_with_fence(elastic_env):
    import importlib

    shuffle_mod = importlib.import_module(
        "ray_shuffling_data_loader_tpu.shuffle"
    )
    store = _evict_store(elastic_env, budget=1 << 20)
    ctl = elastic_mod.ElasticController(_bare_ctx(store))
    with trace.context(epoch=0):
        cold = store.put_columns({"a": np.arange(4096, dtype=np.int32)})
    with trace.context(epoch=1):
        hot = store.put_columns({"a": np.arange(4096, dtype=np.int32)})
    # Epoch 1 is inside the in-flight window: fenced by construction.
    shuffle_mod._status_begin_trial(2, 1, 1, 1, 0)
    shuffle_mod._status_epoch(1, state="running")
    try:
        stats = ctl.evict_once(force=True)
        assert stats["demoted"] == 1 and stats["dropped"] == 0
        # The demoted segment is physically on the spill tier...
        assert store.tier_of(store._find_segment(cold.object_id)) == (
            "spill"
        )
        # ... still readable in place ...
        assert store.get_columns(cold)["a"][7] == 7
        # ... and the fenced epoch never moved.
        assert store.tier_of(store._find_segment(hot.object_id)) == "shm"
        folded = capacity.ledger()
        assert folded["epochs"]["0"]["shm"]["resident_bytes"] == 0
        assert folded["epochs"]["0"]["spill"]["resident_bytes"] > 0
        assert _events_of("evict.demote")

        # The drop rung: gone from every tier, ledger freed, and a
        # later read raises ObjectLostError — the lineage-recovery
        # trigger (PR 3).
        stats = ctl.evict_once(force_drop=True)
        assert stats["dropped"] == 1
        assert store._find_segment(cold.object_id) is None
        with pytest.raises(ObjectLostError):
            store.get_columns(cold)
        folded = capacity.ledger()
        assert folded["epochs"]["0"]["spill"]["resident_bytes"] == 0
        assert _events_of("evict.drop")
        assert ctl.evicted_bytes > 0
    finally:
        shuffle_mod._status_end_trial()
        store.cleanup()


def test_evictor_pressure_watermarks(elastic_env):
    """Without force, the evictor acts only above the high watermark
    and demotes down to the low watermark — and hardlink-sliced
    segments move all their links together."""
    store = _evict_store(elastic_env, budget=200_000)
    os.environ["RSDL_EVICT_COOLDOWN_S"] = "0"
    ctl = elastic_mod.ElasticController(_bare_ctx(store))
    ctl.evict_cooldown_s = 0.0
    # Under the watermark: nothing moves.
    with trace.context(epoch=0):
        small = store.put_columns({"a": np.zeros(100, np.int32)})
    assert ctl.evict_once()["demoted"] == 0
    # Blow past the high watermark (0.85 * 200k) with sliced segments.
    refs = []
    with trace.context(epoch=0):
        for _ in range(4):
            pending = store.create_columns(
                {"a": ((12000,), np.int32)}
            )
            refs.append(pending.publish_slices([(0, 6000), (6000, 12000)]))
    stats = ctl.evict_once()
    assert stats["demoted"] >= 1
    folded = capacity.ledger()
    budget = 200_000
    assert (
        folded["totals"]["shm"]["resident_bytes"]
        <= ctl.evict_low * budget
    )
    # A demoted sliced segment remains readable through EVERY window ref
    # (all hardlinks moved together).
    for ref in refs[0]:
        cb = store.get_columns(ref)
        assert cb.num_rows == 6000
    store.free(small)
    for pair in refs:
        store.free(pair)
    store.cleanup()


def test_evictor_orders_by_last_touch(elastic_env):
    """ISSUE 11 satellite (ROADMAP 5 residual): cold-epoch ordering is
    by LAST ACCESS, not creation age. Epoch 0 is older but actively
    read (its ledger ``touch`` is the most recent), so under pressure
    the evictor must demote the newer-but-idle epoch 1 first."""
    store = _evict_store(elastic_env, budget=230_000)
    ctl = elastic_mod.ElasticController(_bare_ctx(store))
    ctl.evict_cooldown_s = 0.0
    with trace.context(epoch=0):
        old_hot = store.put_columns(
            {"a": np.arange(25_000, dtype=np.int32)}
        )
    time.sleep(0.02)
    with trace.context(epoch=1):
        new_cold = store.put_columns(
            {"a": np.arange(25_000, dtype=np.int32)}
        )
    time.sleep(0.02)
    # A read refreshes epoch 0's last access (store.get_columns emits
    # the ledger touch op).
    assert store.get_columns(old_hot)["a"][3] == 3
    stats = ctl.evict_once()
    # Pressured (2 x ~100 KB > 0.85 x 230 KB); one demotion reaches the
    # low watermark — and it must be the least-recently-touched epoch.
    assert stats["demoted"] == 1
    assert store.tier_of(store._find_segment(new_cold.object_id)) == (
        "spill"
    )
    assert store.tier_of(store._find_segment(old_hot.object_id)) == "shm"
    store.free([old_hot, new_cold])
    store.cleanup()


def test_evictor_cache_tier_drops_first(elastic_env):
    """The shared decode-cache tier (ledger tier ``cache``) is the
    evictor's first rung: under pressure its segments DROP (they
    re-materialize from Parquet via lineage) before any epoch segment
    is demoted."""
    store = _evict_store(elastic_env, budget=230_000)
    ctl = elastic_mod.ElasticController(_bare_ctx(store))
    ctl.evict_cooldown_s = 0.0
    with trace.context(epoch=0):
        epoch_seg = store.put_columns(
            {"a": np.arange(25_000, dtype=np.int32)}
        )
        pending = store.create_columns(
            {"a": ((25_000,), np.int32)}, ledger_tier="cache"
        )
        pending.columns["a"][...] = 1
        cache_ref = pending.seal()
    folded = capacity.ledger()
    assert folded["totals"]["cache"]["resident_bytes"] > 0
    stats = ctl.evict_once()
    # The cache segment was dropped (first rung) and that alone reached
    # the low watermark — the epoch segment never moved tiers.
    assert stats["dropped"] == 1 and stats["demoted"] == 0
    assert store._find_segment(cache_ref.object_id) is None
    assert store.tier_of(store._find_segment(epoch_seg.object_id)) == (
        "shm"
    )
    folded = capacity.ledger()
    assert folded["totals"]["cache"]["resident_bytes"] == 0
    store.free(epoch_seg)
    store.cleanup()


# ---------------------------------------------------------------------------
# Chaos-lane acceptance: scale-up + drain (crash mid-drain) + eviction
# with lineage re-materialization, audit ok, ledger reconciles to zero
# ---------------------------------------------------------------------------

NUM_FILES = 3
ROWS_PER_FILE = 300
TOTAL_ROWS = NUM_FILES * ROWS_PER_FILE


class CollectingConsumer:
    def __init__(self):
        self.keys = collections.defaultdict(list)
        self.done = collections.defaultdict(bool)

    def consume(self, rank, epoch, batches):
        store = runtime.get_context().store
        for ref in batches:
            cb = store.get_columns(ref)
            self.keys[(epoch, rank)].extend(cb["key"].tolist())
            store.free(ref)

    def producer_done(self, rank, epoch):
        self.done[(epoch, rank)] = True

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


def test_chaos_scale_drain_evict_audit_ok(elastic_env, tmp_path_factory):
    """The ISSUE 10 acceptance run: under an armed fault schedule, (1)
    the controller scales the cluster up with a fresh host agent, (2) a
    drain hits a crash mid-drain and degrades into the chaos-proven
    failover, (3) cold decode-cache segments are evicted shm→spill
    (still readable) then dropped, and the next epoch re-materializes
    them from lineage — with strict audit reconciling exactly-once for
    every epoch and the capacity ledger's per-tier residency folding to
    zero at session cleanup."""
    import importlib

    from ray_shuffling_data_loader_tpu.data_generation import generate_file
    from ray_shuffling_data_loader_tpu.runtime import actor as actor_mod
    from ray_shuffling_data_loader_tpu.runtime.cluster import (
        ClusterScheduler,
        HostAgent,
    )

    shuffle_mod = importlib.import_module(
        "ray_shuffling_data_loader_tpu.shuffle"
    )

    os.environ["RSDL_AUDIT"] = "1"
    os.environ["RSDL_AUDIT_STRICT"] = "1"
    os.environ["RSDL_AUDIT_DIR"] = str(elastic_env / "audit-spool")
    # Low-probability schedule, xN-capped like the CI chaos lane: at
    # most one map crash — recovery must absorb it invisibly.
    os.environ["RSDL_FAULTS"] = "task.map/task:crash-entry:0.05x1"
    os.environ["RSDL_FAULTS_SEED"] = "31"
    # The controller's default upper bound is 2x host cores; this test
    # builds a width-2 cluster and asserts a scale-up *actuates*, which
    # on a 1-core CI host the default bound (2) would correctly refuse.
    # The bound is policy under test elsewhere — pin it out of the way.
    os.environ["RSDL_ELASTIC_MAX_WORKERS"] = "8"
    _audit.refresh_from_env()
    _metrics.refresh_from_env()
    faults.refresh_from_env()

    data_dir = tmp_path_factory.mktemp("elastic-chaos-data")
    files = []
    for i in range(NUM_FILES):
        fname, _ = generate_file(
            i, i * ROWS_PER_FILE, ROWS_PER_FILE, 1, str(data_dir)
        )
        files.append(fname)

    ctx = runtime.init(num_workers=2)
    _audit.begin_run()

    agents = [
        actor_mod.spawn_actor(
            HostAgent,
            ctx.runtime_dir,
            1,
            None,
            runtime_dir=ctx.runtime_dir,
            daemon=False,
        )
        for _ in range(2)
    ]
    sched = ClusterScheduler(list(agents), width=2)

    class _FakeCluster:
        def scheduler(self):
            return sched

    ctx.cluster = _FakeCluster()
    ctl = elastic_mod.ElasticController(ctx)
    try:
        # (1) Scale-up: a fresh agent joins the rotation mid-run.
        assert ctl._scale_up(reason="test-forced")
        assert len(sched.agent_addresses) == 3
        assert ctl.scale_events == 1
        up_events = _events_of("scale.up")
        assert up_events and up_events[-1]["reason"] == "test-forced"
        (added_host_id, added_agent) = ctl._added_agents[-1]

        # Decode caches for every file (the segments the evictor will
        # demote/drop), built under an epoch-0 ambient context so the
        # ledger can prove them cold later.
        cache = shuffle_mod._DecodeCache(enabled=True)
        cache_refs = []
        with telemetry.context(epoch=0):
            for i, fname in enumerate(files):
                refs, cref = shuffle_mod.shuffle_map(
                    fname, i, 4, epoch=0, seed=7, publish_cache=True
                )
                ctx.store.free(refs)
                assert cref is not None
                cache.register(
                    i, shuffle_mod._ResolvedMapResult((None, cref))
                )
                cache_refs.append(cref)

        consumer = CollectingConsumer()

        def run_epoch(epoch):
            thread = shuffle_mod.shuffle_epoch(
                epoch, files, consumer, num_reducers=4, num_trainers=1,
                seed=7, decode_cache=cache,
            )
            thread.join()
            assert thread.error is None, thread.error
            keys = consumer.keys[(epoch, 0)]
            assert sorted(keys) == list(range(TOTAL_ROWS))

        run_epoch(0)

        # (2) Graceful drain of the scale-up agent — with a crash mid-
        # drain: the agent dies while a task is still in flight on it,
        # so the planned path must degrade into _drop_agent failover.
        sched._inflight_adjust(added_agent.address, +1)
        os.kill(added_agent.pid, signal.SIGKILL)
        outcome = ctl.drain_host(
            added_agent, host_id=added_host_id, deadline_s=20.0
        )
        assert outcome == "backstop"
        assert len(sched.agent_addresses) == 2
        assert _events_of("scale.drain")
        assert _events_of("scale.drain_backstop")

        # The next epoch still reconciles over the surviving agents.
        run_epoch(1)

        # (3) Tiered eviction of the (now-cold) epoch-0 caches: demote
        # shm→spill — must stay readable in place...
        stats = ctl.evict_once(force=True)
        assert stats["demoted"] >= len(files)
        for cref in cache_refs:
            path = ctx.store._find_segment(cref.object_id)
            assert path is not None
            assert ctx.store.tier_of(path) == "spill"
            assert ctx.store.get_columns(cref).num_rows == ROWS_PER_FILE
        # ... then drop: the segments are gone, and the next epoch's
        # map tasks re-materialize from the Parquet lineage (PR 3's
        # recovery path) instead of failing the epoch.
        stats = ctl.evict_once(force_drop=True)
        assert stats["dropped"] >= len(files)
        assert ctx.store._find_segment(cache_refs[0].object_id) is None
        retries_before = _counter("recovery.stage_retries")
        run_epoch(2)
        assert _counter("recovery.stage_retries") > retries_before

        # Exactly-once, every epoch, under all of the above.
        verdicts = _audit.reconcile([0, 1, 2])
        assert verdicts and all(v["ok"] is True for v in verdicts), (
            verdicts
        )

        # The ledger's acceptance criterion: per-tier residency
        # reconciles to ZERO at session cleanup.
        cache.free_all()
        ctx.store.cleanup()
        folded = capacity.ledger()
        assert folded["totals"]["shm"]["resident_bytes"] == 0
        assert folded["totals"]["spill"]["resident_bytes"] == 0
        assert folded["live_segments"] == 0

        summary = ctl.summary()
        assert summary["scale_events"] == 1
        assert summary["drains"] == 1
        assert summary["evicted_gb"] > 0
    finally:
        ctx.cluster = None
        sched.shutdown()
        for agent in agents:
            try:
                agent.terminate(grace_period_s=2.0)
            except Exception:
                pass


def _counter(name_prefix: str) -> float:
    snap = _metrics.registry.snapshot()
    return sum(
        v for k, v in snap.items() if k.startswith(name_prefix)
    )


# ---------------------------------------------------------------------------
# Zero-overhead acceptance (satellite): RSDL_ELASTIC unset
# ---------------------------------------------------------------------------

_ZERO_OVERHEAD_SCRIPT = r"""
import os, sys, threading
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["RSDL_METRICS"] = "1"  # metrics ON; elastic still must not load
import numpy as np
from ray_shuffling_data_loader_tpu import runtime

ctx = runtime.init(num_workers=1)
store = ctx.store
ref = store.put_columns({{"a": np.arange(64, dtype=np.int32)}})
store.free(ref)
assert "ray_shuffling_data_loader_tpu.runtime.elastic" not in sys.modules
assert not any(
    t.name == "rsdl-elastic" for t in threading.enumerate()
), [t.name for t in threading.enumerate()]
# No transition records: the ledger (metrics are on, so it exists)
# carries only create/delete ops — nothing ever demoted or re-homed.
from ray_shuffling_data_loader_tpu.telemetry import capacity
ops = {{r["op"] for r in capacity.load_records()}}
assert "transition" not in ops, ops
runtime.shutdown()
print("ELASTIC-ZERO-OVERHEAD-OK")
"""


def test_zero_overhead_when_elastic_unset():
    """Satellite acceptance: with RSDL_ELASTIC unset (metrics on or
    off), runtime/elastic is never imported, no control-loop thread
    exists, and no ledger transition record is produced — proven in a
    fresh interpreter (the PR 7/9 recipe)."""
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("RSDL_")
    }
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            _ZERO_OVERHEAD_SCRIPT.format(repo=_REPO),
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "ELASTIC-ZERO-OVERHEAD-OK" in proc.stdout
