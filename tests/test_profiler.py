"""Continuous profiling plane tests (ISSUE 17): sampler lifecycle +
Hz clamp, collapsed-stack folding, phase/epoch tagging through the
phases join, mixed-Hz multi-process spool merge, the /profile and
/profile/flame endpoint pages, digest diff math both directions, the
CLI/report exit-code policies, and the zero-overhead-off
fresh-interpreter proof."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from ray_shuffling_data_loader_tpu.telemetry import obs_server, phases
from ray_shuffling_data_loader_tpu.telemetry import profiler

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ENV = (
    "RSDL_PROFILE",
    "RSDL_PROFILE_HZ",
    "RSDL_PROFILE_DIR",
    "RSDL_PROFILE_TOP_N",
    "RSDL_METRICS",
)


@pytest.fixture
def profile_on(tmp_path):
    """Profiler armed, spooling to a per-test dir; fully unwound on
    teardown (env restored, cached gate + aggregate cleared) so the
    rest of the suite keeps its telemetry-off default."""
    saved = {k: os.environ.get(k) for k in _ENV}
    spool = str(tmp_path / "profiles")
    os.environ["RSDL_PROFILE"] = "1"
    os.environ["RSDL_PROFILE_DIR"] = spool
    for k in ("RSDL_PROFILE_HZ", "RSDL_PROFILE_TOP_N", "RSDL_METRICS"):
        os.environ.pop(k, None)
    profiler.refresh_from_env()
    phases.refresh_from_env()
    profiler.reset()
    yield spool
    profiler.stop()
    profiler.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    profiler.refresh_from_env()
    phases.refresh_from_env()


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------


def test_hz_default_and_clamp(profile_on):
    """Default 67 Hz (off-round by design); RSDL_PROFILE_HZ overrides;
    typos and absurd values clamp to [1, 500] instead of wedging every
    process in its own profiler."""
    assert profiler.hz() == 67.0
    for raw, want in (
        ("200", 200.0),
        ("6700", 500.0),
        ("0.1", 1.0),
        ("junk", 67.0),
    ):
        os.environ["RSDL_PROFILE_HZ"] = raw
        assert profiler.hz() == want, raw
    os.environ.pop("RSDL_PROFILE_HZ", None)
    os.environ["RSDL_PROFILE_TOP_N"] = "7"
    assert profiler.top_n_default() == 7
    os.environ["RSDL_PROFILE_TOP_N"] = "junk"
    assert profiler.top_n_default() == 20


# ---------------------------------------------------------------------------
# Sampler lifecycle + folding
# ---------------------------------------------------------------------------


def _named_threads():
    return {t.name for t in threading.enumerate()}


def test_sampler_lifecycle_and_spool(profile_on):
    """start() spawns ONE daemon sampler thread (idempotent), samples
    accumulate while it runs, and stop() joins it and leaves the final
    aggregate spooled as this process's profile-*.json."""
    assert not profiler.running()
    profiler.start(period=0.005)
    try:
        assert profiler.running()
        thread = next(
            t for t in threading.enumerate() if t.name == "rsdl-profiler"
        )
        assert thread.daemon
        profiler.start(period=0.005)  # idempotent: still one thread
        assert [
            t for t in threading.enumerate() if t.name == "rsdl-profiler"
        ] == [thread]
        deadline = time.time() + 10
        while time.time() < deadline:
            if profiler.snapshot()["samples"] >= 5:
                break
            time.sleep(0.01)
    finally:
        profiler.stop()
    assert not profiler.running()
    assert "rsdl-profiler" not in _named_threads()
    records = profiler.load_records(profile_on)
    assert len(records) == 1
    rec = records[0]
    assert rec["samples"] >= 5
    assert rec["source"]["pid"] == os.getpid()
    assert rec["stacks"], "sampler folded no stacks"
    # Folded format: root-first, thread-name prefixed, leaf last.
    stack = rec["stacks"][0]["stack"]
    assert stack.startswith("thread:")
    assert all(":" in part for part in stack.split(";"))


def test_tick_folds_other_threads_not_itself(profile_on):
    """_tick() folds every live thread EXCEPT the caller, root-first
    with the parked test function on the path and the wait leaf last."""
    evt = threading.Event()

    def _parked_probe():
        evt.wait(timeout=30)

    t = threading.Thread(target=_parked_probe, name="probe", daemon=True)
    t.start()
    try:
        time.sleep(0.05)  # let the probe reach its wait()
        profiler.reset()
        folded = profiler._tick()
        assert folded >= 1
        snap = profiler.snapshot()
        assert snap["samples"] == 1
        probe = [
            s for s in snap["stacks"]
            if s["stack"].startswith("thread:probe;")
        ]
        assert probe, [s["stack"] for s in snap["stacks"]]
        frames = probe[0]["stack"].split(";")
        park_idx = [
            i for i, f in enumerate(frames)
            if f.endswith(":_parked_probe")
        ]
        assert park_idx, frames
        # Leaf (last) is deeper than the parked function: wait() inside.
        assert park_idx[0] < len(frames) - 1
        assert "threading:" in frames[-1]
        # The sampling thread itself never self-samples.
        me = threading.current_thread().name
        assert not any(
            s["stack"].startswith(f"thread:{me};")
            for s in snap["stacks"]
        )
    finally:
        evt.set()
        t.join(timeout=10)


def test_samples_tagged_with_open_phase(profile_on):
    """A thread inside a phases.py phase gets stage/phase/epoch tags on
    its samples — the cross-thread join the flamegraph stage roots and
    the per-stage attribution ride on."""
    ready, release = threading.Event(), threading.Event()

    def _staged():
        prof = phases.stage_profiler("reduce", epoch=3, reducer=1)
        with prof.phase("gather"):
            ready.set()
            release.wait(timeout=30)

    t = threading.Thread(target=_staged, name="staged", daemon=True)
    t.start()
    try:
        assert ready.wait(timeout=10)
        profiler.reset()
        profiler._tick()
        snap = profiler.snapshot()
        tagged = [
            s for s in snap["stacks"]
            if s["stack"].startswith("thread:staged;")
        ]
        assert tagged, [s["stack"] for s in snap["stacks"]]
        tags = tagged[0]["tags"]
        assert tags["stage"] == "reduce"
        assert tags["phase"] == "gather"
        assert tags["epoch"] == "3"
    finally:
        release.set()
        t.join(timeout=10)
    # Phase closed: the same thread's next sample is untagged.
    assert threading.get_ident() not in phases.active_phases() or True
    assert not any(
        ident == t.ident for ident in phases.active_phases()
    ), "closed phase leaked in the active-phase table"


def test_flush_nothing_to_say(profile_on):
    """No samples -> no spool file (flush returns None, dir untouched)."""
    profiler.reset()
    assert profiler.flush() is None
    assert not os.path.exists(os.path.join(profile_on, "nonexistent"))
    assert profiler.load_records(profile_on) == []


# ---------------------------------------------------------------------------
# Merge / analysis (pure functions over records)
# ---------------------------------------------------------------------------


def _record(role, pid, hz, stacks):
    return {
        "source": {"role": role, "host": "h", "pid": pid},
        "ts": 1.0,
        "t0": 0.0,
        "hz": hz,
        "samples": sum(s["count"] for s in stacks),
        "stacks": stacks,
    }


def _write(spool, rec):
    os.makedirs(spool, exist_ok=True)
    path = os.path.join(
        spool, f"profile-{rec['source']['role']}-{rec['source']['pid']}.json"
    )
    with open(path, "w") as f:
        json.dump(rec, f)


def test_mixed_hz_spool_merge_and_filters(tmp_path):
    """Two processes sampling at DIFFERENT rates merge correctly: each
    record's counts convert at its own hz (count/hz seconds), identical
    (stack, tags) keys fold, and stage/epoch filters cut at sample
    granularity."""
    spool = str(tmp_path / "profiles")
    shared = {"stack": "thread:MainThread;a:f;b:g", "count": 100,
              "tags": {"stage": "map"}}
    _write(spool, _record("task", 11, 50.0, [
        dict(shared),
        {"stack": "thread:MainThread;a:f;c:h", "count": 50,
         "tags": {"stage": "reduce", "epoch": "2"}},
    ]))
    _write(spool, _record("task", 12, 100.0, [dict(shared)]))
    agg = profiler.aggregate_profiles(directory=spool, include_local=False)
    assert len(agg["sources"]) == 2
    assert agg["samples"] == 250
    merged = {s["stack"]: s for s in agg["stacks"]}
    fold = merged["thread:MainThread;a:f;b:g"]
    assert fold["count"] == 200
    # 100/50Hz + 100/100Hz = 3.0s — NOT 200 at either single rate.
    assert fold["seconds"] == pytest.approx(3.0)
    assert agg["seconds"] == pytest.approx(3.0 + 50 / 50.0)

    only_map = profiler.aggregate_profiles(
        directory=spool, include_local=False, stage="map"
    )
    assert [s["stack"] for s in only_map["stacks"]] == [
        "thread:MainThread;a:f;b:g"
    ]
    only_e2 = profiler.aggregate_profiles(
        directory=spool, include_local=False, epoch="2"
    )
    assert len(only_e2["stacks"]) == 1
    assert only_e2["stacks"][0]["tags"]["epoch"] == "2"


def test_top_table_self_total_and_recursion(tmp_path):
    """Self = leaf samples; total = stacks the frame appears in, counted
    ONCE per stack (recursion does not double-bill); per-stage self
    attribution rides each row."""
    agg = {
        "sources": [],
        "samples": 4,
        "seconds": 4.0,
        "stacks": [
            {"stack": "a:f;b:g", "count": 3, "seconds": 3.0,
             "tags": {"stage": "map"}},
            {"stack": "a:f;b:g;a:f", "count": 1, "seconds": 1.0,
             "tags": {}},
        ],
    }
    rows = profiler.top_table(agg, n=10)
    by_frame = {r["frame"]: r for r in rows}
    assert rows[0]["frame"] == "b:g"
    assert by_frame["b:g"]["self_s"] == pytest.approx(3.0)
    assert by_frame["b:g"]["self_frac"] == pytest.approx(0.75)
    assert by_frame["b:g"]["stages"] == {"map": pytest.approx(3.0)}
    # a:f appears twice in the recursive stack but its total counts
    # that stack's second once: 3.0 + 1.0, not 3.0 + 2.0.
    assert by_frame["a:f"]["self_s"] == pytest.approx(1.0)
    assert by_frame["a:f"]["total_s"] == pytest.approx(4.0)
    assert profiler.top_table(agg, n=1)[0]["frame"] == "b:g"


def test_collapsed_text_and_flame_page(tmp_path):
    spool = str(tmp_path / "profiles")
    _write(spool, _record("task", 11, 67.0, [
        {"stack": "thread:MainThread;a:f;b:g", "count": 10,
         "tags": {"stage": "reduce"}},
    ]))
    agg = profiler.aggregate_profiles(directory=spool, include_local=False)
    text = profiler.collapsed_text(agg)
    assert text == "thread:MainThread;a:f;b:g 10\n"
    tagged = profiler.collapsed_text(agg, tagged=True)
    assert tagged.startswith("stage:reduce;thread:MainThread;")
    html = profiler.render_flame_html(agg, title="t")
    assert "<html" in html and "stage:reduce" in html and "b:g" in html


def test_digest_and_diff_both_directions(tmp_path):
    """The ledger digest names top frames by self share; diffing two
    digests splits into regressed/improved by fraction delta and drops
    sub-noise (< 1 point) shifts so clean runs diff to nothing."""
    assert profiler.digest(directory=str(tmp_path / "nope")) is None
    base = {"top": [
        {"frame": "a:f", "self_frac": 0.50},
        {"frame": "b:g", "self_frac": 0.40},
        {"frame": "c:h", "self_frac": 0.10},
    ]}
    head = {"top": [
        {"frame": "a:f", "self_frac": 0.20},   # improved
        {"frame": "b:g", "self_frac": 0.405},  # noise: dropped
        {"frame": "d:k", "self_frac": 0.30},   # regressed (new)
    ]}
    shift = profiler.diff_digests(base, head)
    regressed = {r["frame"]: r for r in shift["regressed"]}
    improved = {r["frame"]: r for r in shift["improved"]}
    assert set(regressed) == {"d:k"}
    assert regressed["d:k"]["base_frac"] == pytest.approx(0.0)
    assert regressed["d:k"]["delta_frac"] == pytest.approx(0.30)
    assert set(improved) == {"a:f", "c:h"}
    assert improved["a:f"]["delta_frac"] == pytest.approx(-0.30)
    # Symmetric the other way around.
    back = profiler.diff_digests(head, base)
    assert {r["frame"] for r in back["regressed"]} == {"a:f", "c:h"}
    assert {r["frame"] for r in back["improved"]} == {"d:k"}


# ---------------------------------------------------------------------------
# Endpoint pages
# ---------------------------------------------------------------------------


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode()


def test_profile_endpoint_pages(profile_on):
    """/profile serves the merged JSON view (filterable), ?collapsed=1
    the folded text, and /profile/flame the self-contained HTML page."""
    _write(profile_on, _record("task", 11, 67.0, [
        {"stack": "thread:MainThread;a:f;b:g", "count": 60,
         "tags": {"stage": "reduce"}},
        {"stack": "thread:MainThread;a:f;c:h", "count": 40,
         "tags": {"stage": "map"}},
    ]))
    port = obs_server.start(0)
    try:
        base = f"http://127.0.0.1:{port}"
        _, ctype, body = _get(base + "/profile")
        page = json.loads(body)
        assert "json" in ctype
        assert page["samples"] == 100
        assert page["sampler_running"] is False
        assert page["hz"] == 67.0
        assert len(page["sources"]) == 1
        assert page["top"][0]["frame"] == "b:g"
        assert "stage:reduce;" in page["collapsed"]

        _, _, body = _get(base + "/profile?stage=map&top=5")
        filtered = json.loads(body)
        assert filtered["samples"] == 100  # record-level total
        assert [r["frame"] for r in filtered["top"]] == ["c:h"]

        _, ctype, body = _get(base + "/profile?collapsed=1")
        assert "text/plain" in ctype
        assert "thread:MainThread;a:f;b:g 60" in body

        _, ctype, body = _get(base + "/profile/flame?stage=reduce")
        assert "html" in ctype
        assert "b:g" in body and "c:h" not in body
    finally:
        obs_server.stop()


# ---------------------------------------------------------------------------
# CLI / report exit-code policy
# ---------------------------------------------------------------------------


def _run_tool(tool, *args, env_extra=None):
    env = {**os.environ, "PYTHONPATH": _REPO}
    for k in _ENV:
        env.pop(k, None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", tool), *args],
        capture_output=True, text=True, timeout=120, env=env,
    )


def test_rsdl_prof_cli(tmp_path):
    """top/flame/diff from spool dirs; exit 3 when no data exists."""
    base_dir, head_dir = str(tmp_path / "base"), str(tmp_path / "head")
    _write(base_dir, _record("task", 1, 67.0, [
        {"stack": "thread:M;a:f;b:g", "count": 90, "tags": {}},
        {"stack": "thread:M;a:f;c:h", "count": 10, "tags": {}},
    ]))
    _write(head_dir, _record("task", 1, 67.0, [
        {"stack": "thread:M;a:f;b:g", "count": 10, "tags": {}},
        {"stack": "thread:M;a:f;c:h", "count": 90, "tags": {}},
    ]))
    out = _run_tool("rsdl_prof.py", "top", "--dir", base_dir, "--json")
    assert out.returncode == 0, out.stderr
    top = json.loads(out.stdout)
    assert top["top"][0]["frame"] == "b:g"

    flame = str(tmp_path / "flame.html")
    out = _run_tool("rsdl_prof.py", "flame", "--dir", base_dir,
                    "--out", flame)
    assert out.returncode == 0, out.stderr
    assert "<html" in open(flame).read()

    out = _run_tool("rsdl_prof.py", "diff", base_dir, head_dir, "--json")
    assert out.returncode == 0, out.stderr
    shift = json.loads(out.stdout)
    assert shift["regressed"][0]["frame"] == "c:h"
    assert shift["improved"][0]["frame"] == "b:g"

    out = _run_tool("rsdl_prof.py", "top", "--dir", str(tmp_path / "no"))
    assert out.returncode == 3
    assert "no profile data" in out.stderr


def test_epoch_report_profile_join_policy(tmp_path):
    """--profile follows the zero-coverage rule: a never-produced spool
    is merely noted (exit 0 alongside other data), a present-but-empty
    one exits 3, and a populated one renders the hot-frames table."""
    spool = str(tmp_path / "profiles")
    _write(spool, _record("task", 11, 67.0, [
        {"stack": "thread:M;shuffle:_gather_rows", "count": 100,
         "tags": {"stage": "reduce"}},
    ]))
    out = _run_tool("epoch_report.py", "--profile", spool)
    assert out.returncode == 0, out.stderr
    assert "hot frames (profile)" in out.stdout
    assert "shuffle:_gather_rows" in out.stdout

    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps(
        {"metric": "m", "value": 1.0, "unit": "GB/s"}
    ))
    out = _run_tool("epoch_report.py", "--bench", str(bench),
                    "--profile", str(tmp_path / "never-made"))
    assert out.returncode == 0, out.stderr
    assert "no profile spool present" in out.stderr

    empty = str(tmp_path / "empty")
    _write(empty, _record("task", 12, 67.0, []))
    out = _run_tool("epoch_report.py", "--bench", str(bench),
                    "--profile", empty)
    assert out.returncode == 3
    assert "present but empty" in out.stderr


# ---------------------------------------------------------------------------
# Zero-overhead off
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_profile_off_never_imports_plane(tmp_path):
    """RSDL_PROFILE unset: a fresh interpreter running a whole shuffle
    never imports the profiler module, starts no sampler thread, and
    writes no profile spool anywhere under its cwd — the exact
    zero-overhead contract of the other gated planes."""
    code = """
import os, sys, threading
for k in list(os.environ):
    if k.startswith("RSDL_"):
        del os.environ[k]
os.environ["JAX_PLATFORMS"] = "cpu"
from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_file
from ray_shuffling_data_loader_tpu.shuffle import BatchConsumer, shuffle

class C(BatchConsumer):
    def consume(self, rank, epoch, batches): pass
    def producer_done(self, rank, epoch): pass
    def wait_until_ready(self, epoch): pass
    def wait_until_all_epochs_done(self): pass

files = [generate_file(0, 0, 128, 1, os.getcwd())[0]]
runtime.init(num_workers=1)
shuffle(files, C(), num_epochs=1, num_reducers=1, num_trainers=1, seed=1)
assert not any(
    t.name == "rsdl-profiler" for t in threading.enumerate()
), "sampler thread running while off"
runtime.shutdown()
assert (
    "ray_shuffling_data_loader_tpu.telemetry.profiler" not in sys.modules
), "profiler imported on a profile-off run"
spooled = [
    os.path.join(d, f)
    for d, _, fs in os.walk(os.getcwd())
    for f in fs
    if f.startswith("profile-") and f.endswith(".json")
]
assert not spooled, spooled
print("PROFILE_ZERO_OVERHEAD_OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": _REPO},
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr
    assert "PROFILE_ZERO_OVERHEAD_OK" in out.stdout
