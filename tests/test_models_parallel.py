"""Model + parallel layer tests on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from jax_compat import needs_kernel_partitioning_apis

from ray_shuffling_data_loader_tpu.models import (
    TabularDLRM,
    dlrm_for_data_spec,
    example_features,
)
from ray_shuffling_data_loader_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    adasum_reduce,
    batch_sharding,
    init_state,
    make_mesh,
    make_psum_train_step,
    make_train_step,
    param_spec,
)


def small_model():
    return dlrm_for_data_spec(embed_dim=8, top_mlp=(32, 16), vocab_cap=1000)


def test_forward_shapes():
    model = small_model()
    feats = example_features(model, 32)
    params = model.init(jax.random.key(0), feats)
    logits = model.apply(params, feats)
    assert logits.shape == (32,)
    assert logits.dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(logits)))


def test_param_spec_rules():
    mesh = make_mesh(model_parallelism=2)
    assert param_spec((100_000, 32), mesh) == jax.sharding.PartitionSpec(
        MODEL_AXIS, None
    )
    assert param_spec((100, 32), mesh) == jax.sharding.PartitionSpec()
    assert param_spec((100_001, 32), mesh) == jax.sharding.PartitionSpec()
    mesh1 = make_mesh(model_parallelism=1)
    assert param_spec((100_000, 32), mesh1) == jax.sharding.PartitionSpec()


def test_mesh_validation():
    with pytest.raises(ValueError, match="does not divide"):
        make_mesh(model_parallelism=3)


def test_sharded_init_and_step():
    mesh = make_mesh(model_parallelism=2)
    model = small_model()
    feats_host = example_features(model, 16)
    opt = optax.adam(1e-3)
    state, shardings = init_state(
        model, opt, mesh, feats_host, vocab_shard_threshold=512
    )
    table = state.params["params"]["embed_embeddings_name12"]
    assert table.sharding.spec == (MODEL_AXIS, None)
    # Adam moments shard with their tables.
    mu_table = state.opt_state[0].mu["params"]["embed_embeddings_name12"]
    assert mu_table.sharding.spec == (MODEL_AXIS, None)

    step = make_train_step(model, opt, mesh, shardings)
    bsh = batch_sharding(mesh, 1)
    feats = {k: jax.device_put(v, bsh) for k, v in feats_host.items()}
    labels = jax.device_put(jnp.linspace(0, 1, 16, dtype=jnp.float32), bsh)
    state, metrics = step(state, feats, labels)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1


@needs_kernel_partitioning_apis
def test_pallas_interaction_partitions_on_mesh():
    """Pod-capable kernel policy: with ``use_pallas_interaction=True`` the
    fused interaction runs under a multi-device pjit (the
    ``custom_partitioning`` wrapper splits the ``pallas_call`` batch-wise;
    interpret mode on CPU) and matches the XLA reference lowering."""
    mesh = make_mesh()
    model_ref = small_model()
    model_pl = dlrm_for_data_spec(
        embed_dim=8,
        top_mlp=(32, 16),
        vocab_cap=1000,
        use_pallas_interaction=True,
    )
    feats_host = example_features(model_ref, 32)
    params = model_ref.init(jax.random.key(0), feats_host)
    feats = {
        k: jax.device_put(v, batch_sharding(mesh, 0))
        for k, v in feats_host.items()
    }
    # Committed sharded inputs drive the partitioner; no mesh context
    # manager needed.
    logits_pl = jax.jit(model_pl.apply)(params, feats)
    logits_ref = jax.jit(model_ref.apply)(params, feats)
    np.testing.assert_allclose(
        np.asarray(logits_pl), np.asarray(logits_ref), rtol=2e-5, atol=2e-5
    )


@needs_kernel_partitioning_apis
def test_psum_step_matches_pjit_step():
    """Explicit shard_map+psum DP and sharding-driven pjit DP must compute
    the same update."""
    mesh = make_mesh(model_parallelism=1)
    model = small_model()
    feats_host = example_features(model, 16)
    opt = optax.sgd(0.1)
    state_a, shardings = init_state(model, opt, mesh, feats_host)
    state_b = jax.tree.map(lambda x: x.copy(), state_a)

    bsh = batch_sharding(mesh, 1)
    feats = {k: jax.device_put(v, bsh) for k, v in feats_host.items()}
    labels = jax.device_put(jnp.linspace(0, 1, 16, dtype=jnp.float32), bsh)

    pjit_step = make_train_step(
        model, opt, mesh, shardings, donate_state=False
    )
    psum_step = make_psum_train_step(model, opt, mesh)

    sa, ma = pjit_step(state_a, feats, labels)
    sb, mb = psum_step(state_b, feats, labels)
    assert np.isclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)
    la = sa.params["params"]["Dense_0"]["kernel"]
    lb = sb.params["params"]["Dense_0"]["kernel"]
    # bf16 compute + different reduction order (global mean vs per-shard
    # mean-then-pmean) allow small drift.
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=2e-2, atol=1e-4)


@needs_kernel_partitioning_apis
def test_psum_bf16_gradient_reduce_tracks_f32():
    """The bf16-compressed gradient all-reduce (the reference's fp16
    gradient compression analog) must track the exact f32 reduction:
    same loss trajectory within bf16 tolerance over several steps."""
    mesh = make_mesh(model_parallelism=1)
    model = small_model()
    feats_host = example_features(model, 32)
    rng = np.random.default_rng(1)
    labels_host = (rng.random(32) > 0.5).astype(np.float32)
    opt = optax.sgd(0.05)
    state_a, _ = init_state(model, opt, mesh, feats_host)
    state_b = jax.tree.map(lambda x: x.copy(), state_a)

    bsh = batch_sharding(mesh, 1)
    feats = {k: jax.device_put(v, bsh) for k, v in feats_host.items()}
    labels = jax.device_put(labels_host, bsh)

    step_f32 = make_psum_train_step(model, opt, mesh)
    step_bf16 = make_psum_train_step(
        model, opt, mesh, grad_dtype=jnp.bfloat16
    )
    losses_a, losses_b = [], []
    for _ in range(10):
        state_a, ma = step_f32(state_a, feats, labels)
        state_b, mb = step_bf16(state_b, feats, labels)
        losses_a.append(float(ma["loss"]))
        losses_b.append(float(mb["loss"]))
    # Equivalent optimization: both fall, and the curves stay close.
    assert losses_a[-1] < losses_a[0]
    assert losses_b[-1] < losses_b[0]
    np.testing.assert_allclose(losses_a, losses_b, rtol=2e-2, atol=2e-3)
    # Params stay in their original dtype (cast is wire-only).
    ka = state_a.params["params"]["Dense_0"]["kernel"]
    kb = state_b.params["params"]["Dense_0"]["kernel"]
    assert ka.dtype == kb.dtype


def test_loss_decreases():
    mesh = make_mesh(model_parallelism=1)
    model = small_model()
    feats_host = example_features(model, 64)
    rng = np.random.default_rng(0)
    labels_host = (rng.random(64) > 0.5).astype(np.float32)
    opt = optax.adam(5e-3)
    state, shardings = init_state(model, opt, mesh, feats_host)
    step = make_train_step(model, opt, mesh, shardings)
    bsh = batch_sharding(mesh, 1)
    feats = {k: jax.device_put(v, bsh) for k, v in feats_host.items()}
    labels = jax.device_put(labels_host, bsh)
    losses = []
    for _ in range(20):
        state, metrics = step(state, feats, labels)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


# Slow tier: ~57 s — the full 8-device dryrun, which the driver also
# runs standalone every round; the fast lane keeps the unit-level
# parallel tests.
@needs_kernel_partitioning_apis
@pytest.mark.slow
def test_graft_entry_and_dryrun():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (1024,)
    __graft_entry__.dryrun_multichip(8)


@needs_kernel_partitioning_apis
def test_adasum_reduce_orthogonal_adds_parallel_averages():
    """The Adasum operator's two defining limits (Maleki et al.; reference
    ``hvd.Adasum``, ``ray_torch_shuffle.py:192``): mutually orthogonal
    gradients ADD (independent directions preserved), identical gradients
    return themselves (average-like, no magnitude blowup with DP width)."""
    from ray_shuffling_data_loader_tpu.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P


    mesh = make_mesh(model_parallelism=1)
    n = mesh.shape[DATA_AXIS]

    def reduce_rows(x):
        # Each device contributes its row; result replicated like psum.
        g = adasum_reduce(x[0], DATA_AXIS, n)
        return g[None]

    fn = jax.jit(
        shard_map(
            reduce_rows,
            mesh=mesh,
            in_specs=(P(DATA_AXIS, None),),
            out_specs=P(DATA_AXIS, None),
            check_vma=False,
        )
    )
    # Orthogonal one-hots: adasum == plain sum == all-ones.
    eye = jnp.eye(n, dtype=jnp.float32)
    out = np.asarray(fn(eye))
    np.testing.assert_allclose(out, np.ones((n, n)), rtol=1e-6)
    # Identical rows: adasum(g, g, ...) == g, exactly the pmean result.
    same = jnp.tile(jnp.arange(1.0, float(n + 1))[None, :], (n, 1))
    out = np.asarray(fn(same))
    np.testing.assert_allclose(out, np.asarray(same), rtol=1e-6)
    # Zero gradients must not divide by zero.
    out = np.asarray(fn(jnp.zeros((n, n))))
    assert np.all(np.isfinite(out)) and np.allclose(out, 0.0)


@pytest.mark.parametrize("n", [3, 6])
def test_adasum_reduce_non_power_of_two_axis(n):
    """VERDICT r5 item 8 closed: non-power-of-two axes fold the remainder
    into the leading ranks (the Horovod approach) before the butterfly.
    The operator's defining limits must survive the fold-in exactly:
    identical gradients across all n ranks return themselves (the pmean
    result — the vs-mean limit case), mutually orthogonal gradients add,
    zeros stay finite. Also checks replication: every rank must hold the
    same reduced value after the remainder broadcast-back."""
    from ray_shuffling_data_loader_tpu.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), (DATA_AXIS,))

    def reduce_rows(x):
        g = adasum_reduce(x[0], DATA_AXIS, n)
        return g[None]

    fn = jax.jit(
        shard_map(
            reduce_rows,
            mesh=mesh,
            in_specs=(P(DATA_AXIS, None),),
            out_specs=P(DATA_AXIS, None),
            check_vma=False,
        )
    )
    # Orthogonal one-hots: fold-in pairs stay orthogonal, so adasum ==
    # plain sum == all-ones — and identical on every rank (replication
    # through the broadcast-back).
    out = np.asarray(fn(jnp.eye(n, dtype=jnp.float32)))
    np.testing.assert_allclose(out, np.ones((n, n)), rtol=1e-6)
    # Identical rows: adasum(g, ..., g) == g == pmean — the vs-mean
    # limit case on a ragged axis.
    same = jnp.tile(jnp.arange(1.0, float(n + 1))[None, :], (n, 1))
    out = np.asarray(fn(same))
    np.testing.assert_allclose(out, np.asarray(same), rtol=1e-6)
    # Zero gradients must not divide by zero on any fold-in branch.
    out = np.asarray(fn(jnp.zeros((n, n))))
    assert np.all(np.isfinite(out)) and np.allclose(out, 0.0)


@needs_kernel_partitioning_apis
def test_adasum_step_matches_mean_on_identical_shards():
    """Numerical check against plain mean (VERDICT r4 item 5): when every
    device sees the same batch shard the per-device gradients are equal,
    and the Adasum step must reproduce the pmean step exactly (the
    identical-gradient limit)."""
    mesh = make_mesh(model_parallelism=1)
    n = mesh.shape[DATA_AXIS]
    model = small_model()
    per_dev = 4
    feats_one = example_features(model, per_dev)
    # Tile one shard's rows across all devices.
    feats_host = {
        k: np.tile(np.asarray(v), (n,) + (1,) * (v.ndim - 1))
        for k, v in feats_one.items()
    }
    labels_host = np.tile(
        np.linspace(0, 1, per_dev, dtype=np.float32), n
    )
    opt = optax.sgd(0.1)
    state_a, _ = init_state(model, opt, mesh, feats_host)
    state_b = jax.tree.map(lambda x: x.copy(), state_a)

    bsh = batch_sharding(mesh, 1)
    feats = {k: jax.device_put(v, bsh) for k, v in feats_host.items()}
    labels = jax.device_put(labels_host, bsh)

    mean_step = make_psum_train_step(model, opt, mesh)
    adasum_step = make_psum_train_step(model, opt, mesh, grad_reduce="adasum")
    sa, ma = mean_step(state_a, feats, labels)
    sb, mb = adasum_step(state_b, feats, labels)
    assert np.isclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-6)
    ka = np.asarray(sa.params["params"]["Dense_0"]["kernel"])
    kb = np.asarray(sb.params["params"]["Dense_0"]["kernel"])
    np.testing.assert_allclose(ka, kb, rtol=1e-5, atol=1e-7)


@needs_kernel_partitioning_apis
def test_adasum_step_trains():
    """Adasum as the gradient plane actually optimizes (distinct shards),
    including with the bf16 compressed wire dtype."""
    mesh = make_mesh(model_parallelism=1)
    model = small_model()
    feats_host = example_features(model, 32)
    rng = np.random.default_rng(2)
    labels_host = (rng.random(32) > 0.5).astype(np.float32)
    opt = optax.sgd(0.02)
    state, _ = init_state(model, opt, mesh, feats_host)

    bsh = batch_sharding(mesh, 1)
    feats = {k: jax.device_put(v, bsh) for k, v in feats_host.items()}
    labels = jax.device_put(labels_host, bsh)

    step = make_psum_train_step(
        model, opt, mesh, grad_dtype=jnp.bfloat16, grad_reduce="adasum"
    )
    losses = []
    for _ in range(10):
        state, m = step(state, feats, labels)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


@needs_kernel_partitioning_apis
def test_gradient_reduce_option_validation():
    """Config errors fail fast with actionable messages."""
    mesh = make_mesh(model_parallelism=1)
    model = small_model()
    opt = optax.sgd(0.1)
    with pytest.raises(ValueError, match="grad_reduce"):
        make_psum_train_step(model, opt, mesh, grad_reduce="median")
    with pytest.raises(ValueError, match="power-of-two"):
        adasum_reduce({"g": jnp.ones(3)}, DATA_AXIS, 6)
