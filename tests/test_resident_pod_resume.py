"""Pod preemption recovery for the resident loader.

The failure mode a multi-controller pod actually has: losing ANY process
kills the whole SPMD program (collectives cannot continue without its
shards), so "losing a host that owns resident-loader shards" recovers by
RESTART — re-stage from the (immutable) Parquet source and resume the
batch stream from a cursor checkpoint. This test runs that story end to
end with the real components: a 2-process pod iterates mid-epoch, saves
a ``BatchCursor`` through ``CheckpointManager`` (rank-0 writes, all
ranks call — the multi-controller convention), dies without any cleanup
(``os._exit``), and a fresh 2-process pod restores the cursor,
re-stages, and resumes with ``set_epoch(epoch, skip_batches=...)``.
Union of pre-kill and post-restart keys must be exactly-once per epoch.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["RSDL_T_REPO"])

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["RSDL_T_COORD"],
    num_processes=2,
    process_id=int(os.environ["RSDL_T_RANK"]),
)

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.checkpoint import (
    BatchCursor,
    CheckpointManager,
)
from ray_shuffling_data_loader_tpu.data_generation import generate_data
from ray_shuffling_data_loader_tpu.resident import (
    DeviceResidentShufflingDataset,
)

rank = int(os.environ["RSDL_T_RANK"])
rdv = os.environ["RSDL_T_RDV"]
phase = os.environ["RSDL_T_PHASE"]
NUM_ROWS, BATCH = 8000, 1000
STOP_AFTER = 3  # batches before the simulated preemption

runtime.init(num_workers=2)
if rank == 0 and not os.path.isdir(rdv + "/data"):
    generate_data(NUM_ROWS, 3, 2, 0.0, rdv + "/data_tmp")
    os.rename(rdv + "/data_tmp", rdv + "/data")
else:
    deadline = time.time() + 120
    while not os.path.isdir(rdv + "/data"):
        assert time.time() < deadline
        time.sleep(0.2)
filenames = sorted(
    os.path.join(rdv, "data", f)
    for f in os.listdir(rdv + "/data")
    if ".parquet" in f
)

mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
stream_config = {
    "mode": "resident-pod",
    "seed": 11,
    "batch_size": BATCH,
    "num_files": len(filenames),
}


def shard_keys(arr):
    seen, keys = set(), []
    for shard in arr.addressable_shards:
        idx = tuple((s.start, s.stop) for s in shard.index)
        if idx not in seen:
            seen.add(idx)
            keys.extend(np.asarray(shard.data).reshape(-1).tolist())
    return keys


ds = DeviceResidentShufflingDataset(
    filenames,
    num_epochs=2,
    batch_size=BATCH,
    feature_columns=["key", "embeddings_name0"],
    label_column="labels",
    mesh=mesh,
    seed=11,
)
mgr = CheckpointManager(rdv + "/ckpt")

out = {"epochs": {}}

if phase == "a":
    ds.set_epoch(0)
    keys = []
    it = iter(ds)
    for i in range(STOP_AFTER):
        features, label = next(it)
        jax.block_until_ready(label)
        keys.extend(shard_keys(features["key"]))
    out["epochs"]["0"] = keys
    # Every rank calls save (multi-controller convention); rank 0 writes.
    mgr.save(
        STOP_AFTER,
        cursor=BatchCursor(
            epoch=0,
            batches_yielded=STOP_AFTER,
            step=STOP_AFTER,
            config=stream_config,
        ),
    )
    with open(f"{rdv}/keys_{rank}_a.tmp", "w") as f:
        json.dump(out, f)
    os.rename(f"{rdv}/keys_{rank}_a.tmp", f"{rdv}/keys_{rank}_a")
    print("RESPOD_PREEMPT_OK", rank, flush=True)
    # Preemption: no ds.close(), no runtime.shutdown(), no teardown.
    os._exit(0)

# phase == "b": fresh pod, restore and resume.
cursor = mgr.restore_cursor()
assert cursor is not None, "no checkpoint found on restart"
cursor.validate(stream_config)
assert cursor.epoch == 0 and cursor.batches_yielded == STOP_AFTER

ds.set_epoch(cursor.epoch, skip_batches=cursor.batches_yielded)
keys = []
for features, label in ds:
    jax.block_until_ready(label)
    keys.extend(shard_keys(features["key"]))
out["epochs"]["0"] = keys

ds.set_epoch(1)
keys = []
for features, label in ds:
    jax.block_until_ready(label)
    keys.extend(shard_keys(features["key"]))
out["epochs"]["1"] = keys

with open(f"{rdv}/keys_{rank}_b.tmp", "w") as f:
    json.dump(out, f)
os.rename(f"{rdv}/keys_{rank}_b.tmp", f"{rdv}/keys_{rank}_b")
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("done")
runtime.shutdown()
print("RESPOD_RESUME_OK", rank, flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_phase(tmp_path, phase, expect_marker):
    coord = f"127.0.0.1:{_free_port()}"
    procs, logs = [], []
    for rank in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            RSDL_T_REPO=_REPO,
            RSDL_T_COORD=coord,
            RSDL_T_RANK=str(rank),
            RSDL_T_RDV=str(tmp_path),
            RSDL_T_PHASE=phase,
        )
        log = tmp_path / f"rank{rank}_{phase}.log"
        logs.append(log)
        lf = open(log, "w")
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, "-u", "-c", _WORKER],
                    stdout=lf,
                    stderr=subprocess.STDOUT,
                    env=env,
                ),
                lf,
            )
        )
    try:
        for proc, _ in procs:
            proc.wait(timeout=420)
    finally:
        for proc, lf in procs:
            proc.kill()
            proc.wait()
            lf.close()
    for rank, log in enumerate(logs):
        tail = log.read_text()
        assert f"{expect_marker} {rank}" in tail, (
            f"phase {phase} rank {rank} failed:\n{tail[-2000:]}"
        )


def test_pod_preemption_restart_resumes_exactly_once(tmp_path):
    _run_phase(tmp_path, "a", "RESPOD_PREEMPT_OK")
    _run_phase(tmp_path, "b", "RESPOD_RESUME_OK")

    def merged(phase, epoch):
        keys = []
        for rank in range(2):
            with open(tmp_path / f"keys_{rank}_{phase}") as f:
                keys.extend(json.load(f)["epochs"].get(str(epoch), []))
        return keys

    # Epoch 0 = pre-preemption batches + resumed remainder, exactly-once.
    epoch0 = merged("a", 0) + merged("b", 0)
    assert sorted(epoch0) == list(range(8000)), (
        "resumed epoch-0 stream lost or duplicated rows "
        f"(got {len(epoch0)} keys)"
    )
    # Epoch 1 runs wholly after the restart, exactly-once.
    assert sorted(merged("b", 1)) == list(range(8000))
