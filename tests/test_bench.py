"""Contract test for the repo bench: ``bench.py`` must print exactly one
parseable JSON line with the driver-required keys, even when the
accelerator is unreachable (CPU failover).

The bench is the round's key artifact (round 1 was lost to a bring-up
crash); this pins its output contract. Mock-step mode keeps it fast —
a real 250k-row DLRM step on the CPU backend costs ~10 s each.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REQUIRED_KEYS = {"metric", "value", "unit", "vs_baseline", "backend"}


def _run_bench(tmp_path, extra_env):
    env = dict(
        os.environ,
        # Force the failover path DETERMINISTICALLY, independent of this
        # host's accelerator state: the probe subprocess inherits a bogus
        # platform and must fail, after which the bench pins CPU itself.
        # (Without this, the test's outcome would depend on whether a
        # TPU plugin happens to be present/healthy/wedged.)
        JAX_PLATFORMS="rsdl_no_such_platform",
        RSDL_BENCH_INIT_ATTEMPTS="1",
        RSDL_BENCH_INIT_TIMEOUT_S="30",
        RSDL_BENCH_GB="0.01",
        RSDL_BENCH_CPU_GB="0.01",
        RSDL_BENCH_EPOCHS="1",
        # Mock mode bypasses model build/compile/warm-up entirely; the
        # contract under test is the JSON line, not the train step.
        RSDL_BENCH_MOCK_STEP_S="0.01",
    )
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=str(tmp_path),  # .bench_cache is keyed by CACHE_DIR (abs), ok
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [
        line for line in proc.stdout.splitlines() if line.startswith("{")
    ]
    assert len(lines) == 1, f"expected ONE JSON line, got: {proc.stdout!r}"
    result = json.loads(lines[0])
    assert REQUIRED_KEYS <= set(result), result
    assert result["unit"] == "GB/s/chip"
    assert result["value"] > 0, result
    assert "error" not in result, result
    # Failover must be recorded when the accelerator never came up.
    if result["backend"] == "cpu":
        assert "tpu_error" in result, result
    return result


def test_bench_emits_contract_json(tmp_path):
    result = _run_bench(tmp_path, {})
    # Auto never picks resident on the CPU failover backend.
    assert result["loader"] == "mapreduce", result


def test_bench_resident_loader_contract(tmp_path):
    """The loader the real-TPU round-end bench takes (auto-resident on
    an accelerator) must satisfy the same JSON contract — forced here
    since CI has no accelerator."""
    result = _run_bench(tmp_path, {"RSDL_BENCH_RESIDENT": "on"})
    assert result["loader"] == "resident", result
    assert result["staged_gb"] > 0, result


def test_bench_resident_fused_real_step_contract(tmp_path):
    """The path the real-TPU round-end bench takes end to end: resident
    loader + REAL train steps, which the bench fuses into one jitted
    scan per epoch (resident.make_fused_epoch). The JSON contract and a
    finite loss must survive it."""
    result = _run_bench(
        tmp_path,
        # Empty string disables the mock step set by _run_bench's base
        # env, so the real DLRM step (and with it epoch fusion) runs.
        # One device, like the round-end chip: fusion gates on
        # single-device meshes (multi-device CPU compile of the scanned
        # step explodes).
        {
            "RSDL_BENCH_RESIDENT": "on",
            "RSDL_BENCH_MOCK_STEP_S": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        },
    )
    assert result["loader"] == "resident", result
    assert result["value"] > 0, result
    assert result["loss"] is not None and result["loss"] == result["loss"]
    assert result["steps"] >= 1, result


def test_bench_resident_failure_falls_back(tmp_path):
    """An auto-selected resident loader that dies on the real backend
    must not sink the round's number: the bench restarts the timed
    window on the map/reduce loader and records why."""
    result = _run_bench(
        tmp_path,
        {"RSDL_BENCH_RESIDENT": "on", "RSDL_BENCH_FAULT": "resident"},
    )
    assert result["loader"] == "mapreduce", result
    assert "injected resident fault" in result.get("resident_error", "")
    assert result["value"] > 0, result
