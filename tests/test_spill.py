"""Store capacity budgeting + disk spill (SURVEY §7 hard-part 4).

The reference provisions a 110 GiB object store per node with spilling
deliberately disabled (reference ``benchmarks/cluster.yaml:171-181``) — a
dataset over budget dies. Here shared-memory residency is capped
(``RSDL_STORE_CAPACITY_BYTES`` / ``RSDL_STORE_CAPACITY_FRACTION``) and
over-budget segments transparently land in a disk-backed spill dir, so a
dataset larger than the cap completes instead of ENOSPC-ing mid-epoch."""

import os
import subprocess
import sys

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu.runtime.store import ObjectStore

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def capped_store(tmp_path, monkeypatch):
    shm = tmp_path / "shm"
    spill = tmp_path / "spill"
    shm.mkdir()
    monkeypatch.setenv("RSDL_SPILL_DIR", str(spill))
    store = ObjectStore("spillsess", shm_dir=str(shm))
    store.spill_dir = str(spill)
    store.capacity_bytes = 300_000
    yield store
    store.cleanup()


def test_over_budget_segments_spill_and_read_back(capped_store):
    store = capped_store
    refs = []
    for i in range(10):  # 10 x ~80 KB >> 300 KB cap
        refs.append(
            store.put_columns(
                {"x": np.arange(10_000, dtype=np.int64) + i}
            )
        )
    stats = store.store_stats()
    assert stats.spill_bytes > 0, "nothing spilled despite 2.6x the cap"
    shm_bytes = stats.total_bytes - stats.spill_bytes
    # shm residency respects the cap (one segment of slack for the race
    # window documented in _shm_session_bytes).
    assert shm_bytes <= store.capacity_bytes + 90_000
    # Every segment reads back correctly regardless of placement.
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(
            store.get_columns(ref)["x"], np.arange(10_000, dtype=np.int64) + i
        )
    store.free(refs)
    stats = store.store_stats()
    assert stats.num_objects == 0 and stats.total_bytes == 0


def test_spilled_publish_slices_windows(capped_store):
    store = capped_store
    # Fill shm past the cap, then publish a sliced segment: the hardlinked
    # window refs must work from the spill dir too.
    filler = [
        store.put_columns({"x": np.zeros(10_000, dtype=np.int64)})
        for _ in range(5)
    ]
    pending = store.create_columns({"k": ((50_000,), np.dtype(np.int64))})
    pending.columns["k"][...] = np.arange(50_000)
    refs = pending.publish_slices([(0, 40), (40, 50_000)])
    assert os.path.dirname(pending._path) == store.spill_dir
    np.testing.assert_array_equal(
        store.get_columns(refs[0])["k"], np.arange(40)
    )
    np.testing.assert_array_equal(
        store.get_columns(refs[1])["k"], np.arange(40, 50_000)
    )
    store.free(filler)
    store.free(refs)
    assert store.store_stats().num_objects == 0


_E2E_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
from ray_shuffling_data_loader_tpu import ShufflingDataset, runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_data

def main():
    runtime.init(num_workers=2)
    filenames, num_bytes = generate_data(20_000, 4, 1, 0.0, {data_dir!r})
    # The capacity env (set by the test) is ~half of one epoch's working
    # set: the shuffle must spill and still deliver exactly once.
    ds = ShufflingDataset(
        filenames, num_epochs=2, num_trainers=1, batch_size=4_000,
        rank=0, num_reducers=4, seed=3,
    )
    for epoch in range(2):
        ds.set_epoch(epoch)
        keys = sorted(k for b in ds for k in b["key"].tolist())
        assert keys == list(range(20_000)), len(keys)
    stats = runtime.store_stats()
    assert stats.num_objects == 0, f"leak: {{stats}}"
    runtime.shutdown()
    print("SPILL_E2E_PASS", flush=True)

if __name__ == "__main__":
    main()
"""


# Slow tier: ~18 s of deliberate store-overflow churn (integration).
@pytest.mark.slow
def test_shuffle_completes_with_dataset_over_capacity(tmp_path):
    """End-to-end: dataset working set ~2x the shm budget completes
    (VERDICT r1 item 6 'Done' criterion) with spill active."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # 20k rows x ~168 B ~= 3.4 MB logical; map partitions + reduce
        # outputs double that per epoch. 1.5 MB forces heavy spill.
        RSDL_STORE_CAPACITY_BYTES="1500000",
        RSDL_SPILL_DIR=str(tmp_path / "spill"),
        RSDL_SHM_DIR=str(tmp_path / "shm"),
    )
    os.makedirs(tmp_path / "shm")
    script = _E2E_SCRIPT.format(
        repo=_REPO, data_dir=str(tmp_path / "data")
    )
    proc = subprocess.run(
        [sys.executable, "-u", "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 0 and "SPILL_E2E_PASS" in proc.stdout, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-3000:]}"
    )
