"""ShufflingDataset end-to-end tests: exact-size re-batching, carry-over
across reducer outputs, drop_last, epoch guard, exactly-once delivery, and
multi-trainer sharding. Covers the reference's smoke-run-only territory
(``dataset.py:208-252``) with real assertions."""

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import ShufflingDataset
from ray_shuffling_data_loader_tpu.data_generation import generate_data


@pytest.fixture(scope="module")
def dataset_files(local_runtime, tmp_path_factory):
    data_dir = tmp_path_factory.mktemp("ds-data")
    filenames, _ = generate_data(
        num_rows=3000,
        num_files=3,
        num_row_groups_per_file=1,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )
    return filenames


def _collect_epoch(ds, epoch):
    ds.set_epoch(epoch)
    batches = list(ds)
    return batches


def test_single_trainer_batches(local_runtime, dataset_files):
    num_epochs = 2
    batch_size = 256
    ds = ShufflingDataset(
        dataset_files,
        num_epochs=num_epochs,
        num_trainers=1,
        batch_size=batch_size,
        rank=0,
        num_reducers=4,
        queue_name="q-single",
        seed=1,
    )
    for epoch in range(num_epochs):
        batches = _collect_epoch(ds, epoch)
        # 3000 rows / 256 -> 11 full + 1 partial
        assert [b.num_rows for b in batches[:-1]] == [batch_size] * 11
        assert batches[-1].num_rows == 3000 - 11 * batch_size
        keys = np.concatenate([b["key"] for b in batches])
        assert sorted(keys.tolist()) == list(range(3000))


def test_drop_last(local_runtime, dataset_files):
    ds = ShufflingDataset(
        dataset_files,
        num_epochs=1,
        num_trainers=1,
        batch_size=256,
        rank=0,
        num_reducers=4,
        drop_last=True,
        queue_name="q-droplast",
    )
    batches = _collect_epoch(ds, 0)
    assert all(b.num_rows == 256 for b in batches)
    assert len(batches) == 3000 // 256


def test_epoch_guard(local_runtime, dataset_files):
    ds = ShufflingDataset(
        dataset_files,
        num_epochs=1,
        num_trainers=1,
        batch_size=500,
        rank=0,
        num_reducers=2,
        queue_name="q-guard",
    )
    with pytest.raises(ValueError, match="set_epoch"):
        iter(ds).__next__()
    batches = _collect_epoch(ds, 0)
    assert batches
    with pytest.raises(ValueError, match="set_epoch"):
        iter(ds).__next__()  # same epoch again without set_epoch


def test_multi_trainer_disjoint_shards(local_runtime, dataset_files):
    """Two trainer ranks in threads: shards are disjoint and exhaustive."""
    import threading

    num_trainers = 2
    results = {}

    def run_rank(rank):
        ds = ShufflingDataset(
            dataset_files,
            num_epochs=1,
            num_trainers=num_trainers,
            batch_size=200,
            rank=rank,
            num_reducers=4,
            queue_name="q-multi",
            seed=3,
        )
        ds.set_epoch(0)
        results[rank] = np.concatenate(
            [b["key"] for b in ds]
        ).tolist()

    threads = [
        threading.Thread(target=run_rank, args=(r,))
        for r in range(num_trainers)
    ]
    # Rank 0 must construct first (it owns the queue).
    threads[0].start()
    import time

    time.sleep(0.5)
    threads[1].start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()
    all_keys = results[0] + results[1]
    assert sorted(all_keys) == list(range(3000))
    assert set(results[0]).isdisjoint(set(results[1]))
    assert len(results[0]) > 0 and len(results[1]) > 0


def test_small_reducer_outputs_tail_not_dropped(local_runtime, dataset_files):
    """Reducer outputs smaller than batch_size must still deliver every row
    (the reference drops these tails — ``dataset.py:160-168``)."""
    ds = ShufflingDataset(
        dataset_files,
        num_epochs=1,
        num_trainers=1,
        batch_size=1000,  # >> per-reducer output (3000/8 = ~375 rows)
        rank=0,
        num_reducers=8,
        queue_name="q-smallred",
    )
    batches = _collect_epoch(ds, 0)
    keys = np.concatenate([b["key"] for b in batches])
    assert sorted(keys.tolist()) == list(range(3000))


def test_row_group_skew_generates_ragged_exactly_once(local_runtime, tmp_path):
    """max_row_group_skew — accepted-but-unimplemented in the reference
    (data_generation.py:33 TODO) — produces deterministic ragged row
    groups here, with no row lost."""
    import pyarrow.parquet as pq

    from ray_shuffling_data_loader_tpu.data_generation import (
        generate_data,
        row_group_sizes,
    )

    files, _ = generate_data(8000, 2, 4, 0.5, str(tmp_path / "skew"))
    sizes, keys = [], []
    for f in files:
        md = pq.ParquetFile(f).metadata
        sizes.extend(
            md.row_group(i).num_rows for i in range(md.num_row_groups)
        )
        keys.append(
            np.asarray(pq.read_table(f, columns=["key"]).column("key"))
        )
    assert np.array_equal(np.sort(np.concatenate(keys)), np.arange(8000))
    assert max(sizes) != min(sizes), "skew produced a uniform layout"
    # Deterministic in (seed, file_index); exact total; bounds checked.
    assert row_group_sizes(4000, 4, 0.5, 0, 0) == row_group_sizes(
        4000, 4, 0.5, 0, 0
    )
    assert sum(row_group_sizes(4001, 4, 0.9, 3, 7)) == 4001
    with pytest.raises(ValueError, match="max_row_group_skew"):
        row_group_sizes(100, 2, 1.5, 0, 0)


def test_two_trainer_ranks_disjoint_exactly_once(local_runtime, tmp_path):
    """Host-level DP delivery, both ranks in one process: rank 0 kicks
    off the shuffle, rank 1 connects by name; per-epoch union across the
    ranks is the dataset exactly once, shards disjoint (reference rank
    split np.array_split, shuffle.py:125-126)."""
    import threading

    from ray_shuffling_data_loader_tpu.data_generation import generate_data

    filenames, _ = generate_data(4000, 4, 1, 0.0, str(tmp_path / "dp2"))
    kwargs = dict(
        num_epochs=2,
        num_trainers=2,
        batch_size=300,
        num_reducers=4,
        queue_name="q-host-2rank",
        seed=9,
    )
    ds0 = ShufflingDataset(filenames, rank=0, **kwargs)
    ds1 = ShufflingDataset(filenames, rank=1, **kwargs)
    got = {0: [], 1: []}
    errors = []

    def consume(rank, ds):
        try:
            for epoch in range(2):
                ds.set_epoch(epoch)
                keys = [np.asarray(b["key"]) for b in ds]
                got[rank].append(
                    np.concatenate(keys)
                    if keys
                    else np.array([], dtype=np.int64)
                )
        except Exception as exc:  # noqa: BLE001
            errors.append((rank, exc))

    threads = [
        threading.Thread(target=consume, args=(r, d), daemon=True)
        for r, d in ((0, ds0), (1, ds1))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
    assert not any(t.is_alive() for t in threads), "rank consumption wedged"
    assert not errors, errors
    for epoch in range(2):
        a, b = got[0][epoch], got[1][epoch]
        assert len(a) and len(b)
        assert not set(a.tolist()) & set(b.tolist()), "rank shards overlap"
        assert np.array_equal(
            np.sort(np.concatenate([a, b])), np.arange(4000)
        ), f"epoch {epoch}: union across ranks not exactly-once"
