"""Device-direct delivery under the audit plane (ISSUE 8): the packed
head/body/tail stream must reconcile exactly-once across every digest
side — map == reduce == delivered == consumed == staged — proving the
layout change moved bytes, not rows. Own module: the runtime's workers
must be spawned AFTER the audit env is set (the ``audit_runtime``
pattern from test_audit.py)."""

import os

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_data
from ray_shuffling_data_loader_tpu.telemetry import audit, metrics

_ENV = ("RSDL_AUDIT", "RSDL_AUDIT_DIR", "RSDL_METRICS", "RSDL_DEVICE_DIRECT")


@pytest.fixture(scope="module")
def dd_audit_runtime(tmp_path_factory):
    saved = {k: os.environ.get(k) for k in _ENV}
    spool = str(tmp_path_factory.mktemp("dd-audit-spool"))
    os.environ["RSDL_AUDIT"] = "1"
    os.environ["RSDL_AUDIT_DIR"] = spool
    os.environ["RSDL_METRICS"] = "1"
    os.environ["RSDL_DEVICE_DIRECT"] = "auto"
    audit.refresh_from_env()
    metrics.refresh_from_env()
    audit.reset(clear_spool=True)
    metrics.reset()
    ctx = runtime.init(num_workers=2)
    yield ctx
    runtime.shutdown()
    audit.reset(clear_spool=True)
    metrics.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    audit.refresh_from_env()
    metrics.refresh_from_env()


@pytest.fixture(scope="module")
def dd_audit_files(dd_audit_runtime, tmp_path_factory):
    data_dir = tmp_path_factory.mktemp("dd-audit-data")
    filenames, _ = generate_data(
        num_rows=4096,
        num_files=2,
        num_row_groups_per_file=1,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )
    return filenames


def test_audit_reconciles_on_device_direct_path(
    dd_audit_runtime, dd_audit_files
):
    """Every epoch's verdict must be ok=True with packed delivery
    engaged: digests fold over logical columns of packed segments on the
    deliver/consume/staged sides."""
    from ray_shuffling_data_loader_tpu.jax_dataset import (
        JaxShufflingDataset,
    )

    ds = JaxShufflingDataset(
        list(dd_audit_files),
        num_epochs=2,
        num_trainers=1,
        batch_size=512,
        rank=0,
        feature_columns=["key"],
        label_column="labels",
        num_reducers=3,
        seed=9,
        drop_last=False,
        queue_name="q-dd-audit",
    )
    for epoch in range(2):
        ds.set_epoch(epoch)
        keys = []
        for features, _label in ds:
            keys.extend(np.asarray(features["key"]).tolist())
        assert sorted(keys) == list(range(4096))
    stats = ds.stats.as_dict()
    assert stats["batches_staged_direct"] > 0, "device-direct never engaged"
    verdicts = audit.verdicts()
    assert len(verdicts) == 2
    for v in verdicts:
        assert v["ok"] is True, v
        assert v["rows_delivered"] == 4096
        assert v["rows_staged"] == 4096
