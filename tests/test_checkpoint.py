"""Checkpoint/resume tests: manager round-trip, retention, atomicity,
cursor validation, sharded state restore, and the core resume property —
``set_epoch(epoch, skip_batches=k)`` reproduces exactly the batches an
uninterrupted run would have yielded after its first ``k``. The reference
has no checkpointing at all (SURVEY §5), so these tests define the new
subsystem's contract."""

import os

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import (
    BatchCursor,
    CheckpointManager,
    ShufflingDataset,
)
from ray_shuffling_data_loader_tpu.data_generation import generate_data


@pytest.fixture(scope="module")
def ckpt_files(local_runtime, tmp_path_factory):
    data_dir = tmp_path_factory.mktemp("ckpt-data")
    filenames, _ = generate_data(
        num_rows=2000,
        num_files=2,
        num_row_groups_per_file=1,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )
    return filenames


def _make_ds(files, queue_name, **kwargs):
    defaults = dict(
        num_epochs=1,
        num_trainers=1,
        batch_size=300,
        rank=0,
        num_reducers=3,
        seed=7,
    )
    defaults.update(kwargs)
    return ShufflingDataset(files, queue_name=queue_name, **defaults)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------


def test_manager_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr.latest_step() is None
    assert mgr.restore_cursor() is None

    cursor = BatchCursor(epoch=3, batches_yielded=17, config={"seed": 1})
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.ones(3)}
    mgr.save(42, cursor=cursor, state=state)

    assert mgr.latest_step() == 42
    got_cursor = mgr.restore_cursor()
    assert got_cursor.epoch == 3
    assert got_cursor.batches_yielded == 17
    assert got_cursor.step == 42
    assert got_cursor.config == {"seed": 1}

    target = {"w": np.zeros((2, 3), np.float32), "b": np.zeros(3)}
    got_state = mgr.restore_state(target)
    np.testing.assert_array_equal(got_state["w"], state["w"])
    np.testing.assert_array_equal(got_state["b"], state["b"])


def test_manager_retention_and_steps(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    for step in (1, 5, 9):
        mgr.save(step, cursor=BatchCursor(epoch=0, batches_yielded=step))
    assert mgr.all_steps() == [5, 9]
    assert mgr.restore_cursor(5).batches_yielded == 5
    # Restoring a pruned step yields None, not an error.
    assert mgr.restore_cursor(1) is None


def test_manager_atomic_no_partial_dirs(tmp_path):
    """A failed save must not leave a visible ckpt- directory."""
    mgr = CheckpointManager(str(tmp_path / "ck"))

    class Boom:
        pass

    with pytest.raises(Exception):
        # flax can't serialize an arbitrary object -> save raises mid-write.
        mgr.save(7, state={"bad": Boom()})
    assert mgr.all_steps() == []


def test_cursor_validation():
    config = BatchCursor.stream_config(
        seed=1,
        batch_size=10,
        num_trainers=2,
        num_reducers=4,
        num_files=3,
        drop_last=False,
    )
    cursor = BatchCursor(epoch=0, batches_yielded=0, config=config)
    cursor.validate(dict(config))  # identical: fine
    with pytest.raises(ValueError, match="batch_size"):
        cursor.validate({**config, "batch_size": 20})


def test_restore_sharded_state(tmp_path):
    """State leaves land with the requested shardings on restore."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    state = {"w": np.arange(8, dtype=np.float32)}
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, state=state)
    restored = mgr.restore_state(
        {"w": np.zeros(8, np.float32)}, shardings={"w": sharding}
    )
    assert restored["w"].sharding == sharding
    np.testing.assert_array_equal(np.asarray(restored["w"]), state["w"])


# ---------------------------------------------------------------------------
# Mid-epoch resume through the dataset
# ---------------------------------------------------------------------------


def test_skip_batches_resumes_stream(local_runtime, ckpt_files):
    """The resumed stream equals the uninterrupted stream's tail, batch for
    batch — the property that makes cursor checkpointing sound."""
    full = _make_ds(ckpt_files, "q-ck-full")
    full.set_epoch(0)
    full_keys = [b["key"].tolist() for b in full]
    assert len(full_keys) == 7  # 2000 rows / 300 -> 6 full + 1 partial

    skip = 3
    resumed = _make_ds(ckpt_files, "q-ck-resume")
    resumed.set_epoch(0, skip_batches=skip)
    resumed_keys = [b["key"].tolist() for b in resumed]
    assert resumed_keys == full_keys[skip:]


def test_skip_all_batches(local_runtime, ckpt_files):
    """Skipping every batch (resume exactly at epoch end) yields nothing but
    still drains and acks the epoch."""
    ds = _make_ds(ckpt_files, "q-ck-skipall")
    ds.set_epoch(0, skip_batches=7)
    assert list(ds) == []


def test_skip_resets_next_epoch(local_runtime, ckpt_files):
    """skip_batches applies only to the epoch it was set for."""
    ds = _make_ds(ckpt_files, "q-ck-reset", num_epochs=2)
    ds.set_epoch(0, skip_batches=5)
    assert len(list(ds)) == 2
    ds.set_epoch(1)
    assert len(list(ds)) == 7


def test_start_epoch_resume(local_runtime, ckpt_files):
    """Epoch-level resume: a dataset constructed with ``start_epoch=1``
    yields epoch 1 exactly as the original run did (absolute epoch indices
    keep the permutations identical), without shuffling epoch 0 at all."""
    full = _make_ds(ckpt_files, "q-ck-se-full", num_epochs=2)
    full.set_epoch(0)
    list(full)
    full.set_epoch(1)
    epoch1 = [b["key"].tolist() for b in full]

    resumed = _make_ds(
        ckpt_files, "q-ck-se-res", num_epochs=2, start_epoch=1
    )
    resumed.set_epoch(1)
    assert [b["key"].tolist() for b in resumed] == epoch1


def test_end_to_end_preemption_replay(local_runtime, ckpt_files, tmp_path):
    """Simulated preemption: after k batches the cursor is checkpointed and
    every later batch of the first run is treated as lost (in a real
    preemption the whole process dies); a fresh dataset resumes from the
    cursor and re-produces exactly the lost tail — union of kept + resumed
    keys = whole dataset, no dupes."""
    mgr = CheckpointManager(str(tmp_path / "ck"))
    config = BatchCursor.stream_config(
        seed=7,
        batch_size=300,
        num_trainers=1,
        num_reducers=3,
        num_files=len(ckpt_files),
        drop_last=False,
    )

    first = _make_ds(ckpt_files, "q-ck-pre1")
    first.set_epoch(0)
    kept = []
    for i, batch in enumerate(first):
        if i <= 1:
            kept.append(batch["key"].tolist())
        if i == 1:  # cursor written right after batch 2
            mgr.save(
                i + 1,
                cursor=BatchCursor(
                    epoch=0, batches_yielded=i + 1, config=config
                ),
            )
        # batches after the checkpoint are discarded ("lost to preemption")

    cursor = mgr.restore_cursor()
    cursor.validate(config)
    resumed = _make_ds(ckpt_files, "q-ck-pre2")
    resumed.set_epoch(cursor.epoch, skip_batches=cursor.batches_yielded)
    for batch in resumed:
        kept.append(batch["key"].tolist())
    all_keys = [k for batch in kept for k in batch]
    assert sorted(all_keys) == list(range(2000))


# ---------------------------------------------------------------------------
# Crash-mid-publish debris (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


def test_torn_publish_debris_never_surfaces_and_ages_out(tmp_path):
    """A writer that died between mkdtemp and the atomic rename leaves a
    ``ckpt-*.tmp-*`` staging dir. Readers must never surface it as a
    checkpoint, and once it is older than the grace window (a single
    writer per directory — old debris can only be a dead writer's) the
    read paths prune it from disk."""
    import json as _json
    import time as _time

    from ray_shuffling_data_loader_tpu import checkpoint as ckpt_mod

    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(3, cursor=BatchCursor(epoch=0, batches_yielded=3))

    # Fabricate the torn checkpoint exactly as save() stages one: the
    # tmp dir even holds a complete cursor.json — only the rename is
    # missing, so nothing about its CONTENT marks it torn.
    debris = tmp_path / "ck" / "ckpt-0000000009.tmp-dead0a"
    debris.mkdir()
    (debris / "cursor.json").write_text(
        _json.dumps({"epoch": 9, "batches_yielded": 9, "step": 9})
    )

    # Young debris: skipped by every reader, but NOT pruned (it may be
    # a live writer's in-flight save on a shared filesystem).
    assert mgr.all_steps() == [3]
    assert mgr.latest_step() == 3
    cursor = mgr.restore_cursor()
    assert cursor is not None and cursor.step == 3
    assert debris.is_dir()

    # Aged past the grace window: the next read prunes it.
    old = _time.time() - ckpt_mod._DEBRIS_GRACE_S - 5
    os.utime(debris, (old, old))
    assert mgr.all_steps() == [3]
    assert not debris.exists()
    # A published checkpoint of the same vintage is untouched.
    assert mgr.restore_cursor().step == 3


def test_debris_prune_never_eats_published_checkpoints(tmp_path):
    """The debris pattern must not match published ``ckpt-*`` dirs even
    when they are old."""
    import time as _time

    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, cursor=BatchCursor(epoch=0, batches_yielded=1))
    published = tmp_path / "ck" / "ckpt-0000000001"
    old = _time.time() - 10_000
    os.utime(published, (old, old))
    assert mgr.all_steps() == [1]
    assert published.is_dir()


# ---------------------------------------------------------------------------
# Cursor stream identity: plan family + journal run join (ISSUE 13)
# ---------------------------------------------------------------------------


def test_cursor_config_captures_plan_family(monkeypatch):
    """The PR 12 plan family postdates the cursor's stream-identity
    config: seed, plan, and blocks/group must all be captured, and a
    plan mismatch must refuse like any other stream change."""
    monkeypatch.delenv("RSDL_SHUFFLE_PLAN", raising=False)
    base = dict(
        seed=1, batch_size=10, num_trainers=2, num_reducers=4,
        num_files=3, drop_last=False,
    )
    config = BatchCursor.stream_config(**base)
    assert config["plan"] == "rowwise"

    monkeypatch.setenv("RSDL_SHUFFLE_PLAN", "block:2")
    assert BatchCursor.stream_config(**base)["plan"] == "block:2"
    # The granularity is part of the identity: block:2 vs block:4 is a
    # different stream even within the same family.
    assert BatchCursor.stream_config(**base, plan="block:4")["plan"] == (
        "block:4"
    )

    cursor = BatchCursor(epoch=0, batches_yielded=0, config=config)
    with pytest.raises(ValueError, match="plan"):
        cursor.validate(BatchCursor.stream_config(**base))
    monkeypatch.delenv("RSDL_SHUFFLE_PLAN", raising=False)
    cursor.validate(BatchCursor.stream_config(**base))


def test_cursor_validation_refusal_paths():
    """Every stream-identity knob refuses on mismatch, with the field
    named; empty configs (legacy cursors) stay permissive."""
    base = dict(
        seed=1, batch_size=10, num_trainers=2, num_reducers=4,
        num_files=3, drop_last=False, plan="rowwise",
    )
    cursor = BatchCursor(
        epoch=0, batches_yielded=0, config=BatchCursor.stream_config(**base)
    )
    for key, val in (
        ("seed", 2),
        ("batch_size", 20),
        ("num_trainers", 1),
        ("num_reducers", 8),
        ("num_files", 4),
        ("drop_last", True),
        ("plan", "block:1"),
    ):
        with pytest.raises(ValueError, match=key):
            cursor.validate(
                BatchCursor.stream_config(**{**base, key: val})
            )
    # Legacy/empty configs never refuse (nothing recorded to compare).
    BatchCursor(epoch=0, batches_yielded=0).validate(
        BatchCursor.stream_config(**base)
    )
    cursor.validate({})
    # A cursor saved BEFORE the plan family existed (non-empty config,
    # no "plan" key) was implicitly rowwise: it must keep resuming
    # under rowwise, and still refuse a block-plan stream.
    legacy = BatchCursor(
        epoch=0, batches_yielded=0,
        config={
            k: v
            for k, v in BatchCursor.stream_config(**base).items()
            if k != "plan"
        },
    )
    legacy.validate(BatchCursor.stream_config(**base))
    with pytest.raises(ValueError, match="plan"):
        legacy.validate(
            BatchCursor.stream_config(**{**base, "plan": "block:2"})
        )


def test_cursor_joins_journal_run_identity(tmp_path, monkeypatch):
    """With the driver's write-ahead journal in flight, ``save`` stamps
    the cursor with the journal's run_id — trainer cursor and driver
    window resume as one recorded run. Without one, the stamp stays
    None (and the journal module is never consulted into existence)."""
    from ray_shuffling_data_loader_tpu.runtime import journal as jmod

    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, cursor=BatchCursor(epoch=0, batches_yielded=1))
    assert mgr.restore_cursor(1).run_id is None

    monkeypatch.setenv("RSDL_JOURNAL", str(tmp_path / "journal"))
    journal = jmod.begin_run({"seed": 1})
    try:
        mgr.save(2, cursor=BatchCursor(epoch=0, batches_yielded=2))
        assert mgr.restore_cursor(2).run_id == journal.run_id
        # Informational only: run_id never participates in validate()
        # (a resumed driver gets a NEW run id for the same stream).
        restored = mgr.restore_cursor(2)
        restored.validate(mgr.restore_cursor(1).config or {})
    finally:
        jmod.end_run(journal)
    mgr.save(3, cursor=BatchCursor(epoch=0, batches_yielded=3))
    assert mgr.restore_cursor(3).run_id is None


# ---------------------------------------------------------------------------
# skip_batches stream equality under the block plan family (ISSUE 13)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def block_files(local_runtime, tmp_path_factory):
    """Multi-row-group dataset: block plans assign row-group-aligned
    blocks, so the fixture needs more groups than the single-group
    ckpt_files to exercise a non-degenerate block permutation."""
    data_dir = tmp_path_factory.mktemp("ckpt-block-data")
    filenames, _ = generate_data(
        num_rows=2000,
        num_files=2,
        num_row_groups_per_file=4,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )
    return filenames


def test_skip_batches_stream_equality_under_block_plan(
    local_runtime, block_files, monkeypatch
):
    """The cursor-resume property holds per plan family: under
    ``RSDL_SHUFFLE_PLAN=block`` the resumed stream equals the
    uninterrupted block-plan stream's tail, and the cursor refuses to
    cross plan families (the PR 12 block plan delivers a genuinely
    different stream than rowwise at the same seed)."""
    monkeypatch.setenv("RSDL_SHUFFLE_PLAN", "block:1")
    config = BatchCursor.stream_config(
        seed=7, batch_size=300, num_trainers=1, num_reducers=3,
        num_files=len(block_files), drop_last=False,
    )
    assert config["plan"] == "block:1"

    full = _make_ds(block_files, "q-ck-blk-full")
    full.set_epoch(0)
    full_keys = [b["key"].tolist() for b in full]
    assert sorted(k for b in full_keys for k in b) == list(range(2000))

    skip = 3
    resumed = _make_ds(block_files, "q-ck-blk-res")
    resumed.set_epoch(0, skip_batches=skip)
    resumed_keys = [b["key"].tolist() for b in resumed]
    assert resumed_keys == full_keys[skip:]

    # Crossing plan families with the same cursor refuses.
    cursor = BatchCursor(epoch=0, batches_yielded=skip, config=config)
    monkeypatch.setenv("RSDL_SHUFFLE_PLAN", "rowwise")
    with pytest.raises(ValueError, match="plan"):
        cursor.validate(
            BatchCursor.stream_config(
                seed=7, batch_size=300, num_trainers=1, num_reducers=3,
                num_files=len(block_files), drop_last=False,
            )
        )
