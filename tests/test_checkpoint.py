"""Checkpoint/resume tests: manager round-trip, retention, atomicity,
cursor validation, sharded state restore, and the core resume property —
``set_epoch(epoch, skip_batches=k)`` reproduces exactly the batches an
uninterrupted run would have yielded after its first ``k``. The reference
has no checkpointing at all (SURVEY §5), so these tests define the new
subsystem's contract."""

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import (
    BatchCursor,
    CheckpointManager,
    ShufflingDataset,
)
from ray_shuffling_data_loader_tpu.data_generation import generate_data


@pytest.fixture(scope="module")
def ckpt_files(local_runtime, tmp_path_factory):
    data_dir = tmp_path_factory.mktemp("ckpt-data")
    filenames, _ = generate_data(
        num_rows=2000,
        num_files=2,
        num_row_groups_per_file=1,
        max_row_group_skew=0.0,
        data_dir=str(data_dir),
    )
    return filenames


def _make_ds(files, queue_name, **kwargs):
    defaults = dict(
        num_epochs=1,
        num_trainers=1,
        batch_size=300,
        rank=0,
        num_reducers=3,
        seed=7,
    )
    defaults.update(kwargs)
    return ShufflingDataset(files, queue_name=queue_name, **defaults)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------


def test_manager_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr.latest_step() is None
    assert mgr.restore_cursor() is None

    cursor = BatchCursor(epoch=3, batches_yielded=17, config={"seed": 1})
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.ones(3)}
    mgr.save(42, cursor=cursor, state=state)

    assert mgr.latest_step() == 42
    got_cursor = mgr.restore_cursor()
    assert got_cursor.epoch == 3
    assert got_cursor.batches_yielded == 17
    assert got_cursor.step == 42
    assert got_cursor.config == {"seed": 1}

    target = {"w": np.zeros((2, 3), np.float32), "b": np.zeros(3)}
    got_state = mgr.restore_state(target)
    np.testing.assert_array_equal(got_state["w"], state["w"])
    np.testing.assert_array_equal(got_state["b"], state["b"])


def test_manager_retention_and_steps(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    for step in (1, 5, 9):
        mgr.save(step, cursor=BatchCursor(epoch=0, batches_yielded=step))
    assert mgr.all_steps() == [5, 9]
    assert mgr.restore_cursor(5).batches_yielded == 5
    # Restoring a pruned step yields None, not an error.
    assert mgr.restore_cursor(1) is None


def test_manager_atomic_no_partial_dirs(tmp_path):
    """A failed save must not leave a visible ckpt- directory."""
    mgr = CheckpointManager(str(tmp_path / "ck"))

    class Boom:
        pass

    with pytest.raises(Exception):
        # flax can't serialize an arbitrary object -> save raises mid-write.
        mgr.save(7, state={"bad": Boom()})
    assert mgr.all_steps() == []


def test_cursor_validation():
    config = BatchCursor.stream_config(
        seed=1,
        batch_size=10,
        num_trainers=2,
        num_reducers=4,
        num_files=3,
        drop_last=False,
    )
    cursor = BatchCursor(epoch=0, batches_yielded=0, config=config)
    cursor.validate(dict(config))  # identical: fine
    with pytest.raises(ValueError, match="batch_size"):
        cursor.validate({**config, "batch_size": 20})


def test_restore_sharded_state(tmp_path):
    """State leaves land with the requested shardings on restore."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    state = {"w": np.arange(8, dtype=np.float32)}
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, state=state)
    restored = mgr.restore_state(
        {"w": np.zeros(8, np.float32)}, shardings={"w": sharding}
    )
    assert restored["w"].sharding == sharding
    np.testing.assert_array_equal(np.asarray(restored["w"]), state["w"])


# ---------------------------------------------------------------------------
# Mid-epoch resume through the dataset
# ---------------------------------------------------------------------------


def test_skip_batches_resumes_stream(local_runtime, ckpt_files):
    """The resumed stream equals the uninterrupted stream's tail, batch for
    batch — the property that makes cursor checkpointing sound."""
    full = _make_ds(ckpt_files, "q-ck-full")
    full.set_epoch(0)
    full_keys = [b["key"].tolist() for b in full]
    assert len(full_keys) == 7  # 2000 rows / 300 -> 6 full + 1 partial

    skip = 3
    resumed = _make_ds(ckpt_files, "q-ck-resume")
    resumed.set_epoch(0, skip_batches=skip)
    resumed_keys = [b["key"].tolist() for b in resumed]
    assert resumed_keys == full_keys[skip:]


def test_skip_all_batches(local_runtime, ckpt_files):
    """Skipping every batch (resume exactly at epoch end) yields nothing but
    still drains and acks the epoch."""
    ds = _make_ds(ckpt_files, "q-ck-skipall")
    ds.set_epoch(0, skip_batches=7)
    assert list(ds) == []


def test_skip_resets_next_epoch(local_runtime, ckpt_files):
    """skip_batches applies only to the epoch it was set for."""
    ds = _make_ds(ckpt_files, "q-ck-reset", num_epochs=2)
    ds.set_epoch(0, skip_batches=5)
    assert len(list(ds)) == 2
    ds.set_epoch(1)
    assert len(list(ds)) == 7


def test_start_epoch_resume(local_runtime, ckpt_files):
    """Epoch-level resume: a dataset constructed with ``start_epoch=1``
    yields epoch 1 exactly as the original run did (absolute epoch indices
    keep the permutations identical), without shuffling epoch 0 at all."""
    full = _make_ds(ckpt_files, "q-ck-se-full", num_epochs=2)
    full.set_epoch(0)
    list(full)
    full.set_epoch(1)
    epoch1 = [b["key"].tolist() for b in full]

    resumed = _make_ds(
        ckpt_files, "q-ck-se-res", num_epochs=2, start_epoch=1
    )
    resumed.set_epoch(1)
    assert [b["key"].tolist() for b in resumed] == epoch1


def test_end_to_end_preemption_replay(local_runtime, ckpt_files, tmp_path):
    """Simulated preemption: after k batches the cursor is checkpointed and
    every later batch of the first run is treated as lost (in a real
    preemption the whole process dies); a fresh dataset resumes from the
    cursor and re-produces exactly the lost tail — union of kept + resumed
    keys = whole dataset, no dupes."""
    mgr = CheckpointManager(str(tmp_path / "ck"))
    config = BatchCursor.stream_config(
        seed=7,
        batch_size=300,
        num_trainers=1,
        num_reducers=3,
        num_files=len(ckpt_files),
        drop_last=False,
    )

    first = _make_ds(ckpt_files, "q-ck-pre1")
    first.set_epoch(0)
    kept = []
    for i, batch in enumerate(first):
        if i <= 1:
            kept.append(batch["key"].tolist())
        if i == 1:  # cursor written right after batch 2
            mgr.save(
                i + 1,
                cursor=BatchCursor(
                    epoch=0, batches_yielded=i + 1, config=config
                ),
            )
        # batches after the checkpoint are discarded ("lost to preemption")

    cursor = mgr.restore_cursor()
    cursor.validate(config)
    resumed = _make_ds(ckpt_files, "q-ck-pre2")
    resumed.set_epoch(cursor.epoch, skip_batches=cursor.batches_yielded)
    for batch in resumed:
        kept.append(batch["key"].tolist())
    all_keys = [k for batch in kept for k in batch]
    assert sorted(all_keys) == list(range(2000))
