"""Pallas flash-attention kernel vs the dense reference, interpreter mode
(the compiled-on-TPU check lives in ``tests/test_ops_tpu.py``'s pattern;
CI has no TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax_compat import needs_kernel_partitioning_apis

from ray_shuffling_data_loader_tpu.ops import attention_reference
from ray_shuffling_data_loader_tpu.ops.flash_attention import flash_attention


def _qkv(shape, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal(shape).astype(np.float32), dtype)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "shape,blocks",
    [
        ((2, 64, 2, 8), (16, 16)),  # multiple kv blocks per q block
        ((1, 56, 2, 8), (16, 24)),  # ragged: seq divides neither block
        ((2, 8, 1, 4), (128, 128)),  # seq smaller than the block
    ],
)
@needs_kernel_partitioning_apis
def test_matches_dense_reference(causal, shape, blocks):
    q, k, v = _qkv(shape, seed=1)
    got = flash_attention(
        q,
        k,
        v,
        causal=causal,
        use_pallas=True,
        block_q=blocks[0],
        block_k=blocks[1],
        interpret=True,
    )
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@needs_kernel_partitioning_apis
def test_bfloat16(seed=3):
    q, k, v = _qkv((2, 32, 2, 8), seed=seed, dtype=jnp.bfloat16)
    got = flash_attention(
        q, k, v, use_pallas=True, block_q=16, block_k=16, interpret=True
    )
    assert got.dtype == jnp.bfloat16
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32),
        np.asarray(want, dtype=np.float32),
        rtol=5e-2,
        atol=5e-2,
    )


@needs_kernel_partitioning_apis
def test_gradients_exact():
    """The custom VJP is the dense reference's gradient — exact."""
    q, k, v = _qkv((1, 32, 2, 8), seed=4)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, use_pallas=True,
                block_q=16, block_k=16, interpret=True,
            )
            ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_f = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    for gf, gd in zip(g_f, g_d):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


@needs_kernel_partitioning_apis
@pytest.mark.parametrize("causal", [False, True])
def test_gradients_multi_chunk_ragged(causal):
    """Backward with several KV chunks and a ragged tail (T=300 over
    128-wide chunks) — the chunked-VJP path the single-chunk test
    misses."""
    q, k, v = _qkv((1, 300, 2, 8), seed=6)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=causal, use_pallas=True, interpret=True
            )
            ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g_f = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    for gf, gd in zip(g_f, g_d):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=1e-3, atol=1e-4
        )


@needs_kernel_partitioning_apis
def test_gradients_sharded_mesh():
    """Forward AND fused backward under a multi-device pjit: the
    custom_partitioning wrappers split both pallas calls batch-wise on
    the 8-device mesh; gradients match the dense reference."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("data",))
    q, k, v = _qkv((8, 64, 2, 8), seed=11)
    sh = NamedSharding(mesh, P("data", None, None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, use_pallas=True, interpret=True
            )
            ** 2
        )

    g_f = jax.jit(jax.grad(loss_flash, (0, 1, 2)))(qs, ks, vs)
    g_d = jax.grad(
        lambda q, k, v: jnp.sum(
            attention_reference(q, k, v, causal=True) ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    for gf, gd in zip(g_f, g_d):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


@needs_kernel_partitioning_apis
def test_flash_backward_xla_escape_hatch(monkeypatch):
    """RSDL_FLASH_BWD=xla routes the VJP through the chunked-XLA
    backward; gradients stay exact."""
    monkeypatch.setenv("RSDL_FLASH_BWD", "xla")
    q, k, v = _qkv((1, 48, 2, 8), seed=12)
    g_f = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(
                q, k, v, causal=True, use_pallas=True, interpret=True,
                block_q=16, block_k=16,
            )
            ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    g_d = jax.grad(
        lambda q, k, v: jnp.sum(
            attention_reference(q, k, v, causal=True) ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    for gf, gd in zip(g_f, g_d):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


def test_xla_fallback_path():
    q, k, v = _qkv((1, 16, 2, 4), seed=5)
    got = flash_attention(q, k, v, use_pallas=False)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
