"""Parallel decode plane tests (ISSUE 11).

Covers: row-group-parallel decode bit-identity against the single-shot
read (odd/skewed row-group sizes, single-row-group files, projection
on/off), row-group selections, the RINAS-style selective schedule's
stream equivalence against the materialized path under a fixed seed,
the cross-epoch shared decode-cache tier (hit + invalidation across
two consecutive ``shuffle()`` calls), pushdown pruned-bytes counters,
and the zero-overhead-off proof for the whole plane.
"""

import importlib
import os
import subprocess
import sys

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.data_generation import generate_data
from ray_shuffling_data_loader_tpu.utils import (
    decode_rowgroup_threads,
    shuffle_plan_label,
    shuffle_plan_spec,
)

sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")


def _sum_metric(snap: dict, name: str) -> float:
    """Total of a counter across its labeled series (ISSUE 12 put
    ``{schedule, plan}`` labels on the decode counters) — the shared
    ``export.labeled_sum`` fold, totals only."""
    from ray_shuffling_data_loader_tpu.telemetry import export

    return export.labeled_sum(snap, name)[0]


@pytest.fixture(scope="module")
def rg_dataset(local_runtime, tmp_path_factory):
    """Skewed row groups (odd sizes) — the decode plan's hard case."""
    data_dir = tmp_path_factory.mktemp("decode-plane-data")
    filenames, num_bytes = generate_data(
        num_rows=3000,
        num_files=3,
        num_row_groups_per_file=5,
        max_row_group_skew=0.5,
        data_dir=str(data_dir),
    )
    assert num_bytes > 0
    return filenames


@pytest.fixture
def shared_cache_clean():
    """Isolate shared-registry state per test (the registry is
    process-level by design)."""
    sh.shared_decode_cache_clear()
    yield
    sh.shared_decode_cache_clear()


class _Collecting(sh.BatchConsumer):
    def __init__(self):
        import collections

        self.keys = collections.defaultdict(list)
        self.done = collections.defaultdict(bool)

    def consume(self, rank, epoch, batches):
        from ray_shuffling_data_loader_tpu.runtime.store import (
            logical_columns,
        )

        store = runtime.get_context().store
        for ref in batches:
            cb = store.get_columns(ref)
            self.keys[(epoch, rank)].extend(
                np.asarray(logical_columns(cb)["key"]).tolist()
            )
            store.free(ref)

    def producer_done(self, rank, epoch):
        self.done[(epoch, rank)] = True

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


# -- row-group-parallel decode bit-identity ---------------------------------


@pytest.mark.parametrize("threads", [2, 3])
@pytest.mark.parametrize("proj", [None, ["key", "labels"]])
def test_rowgroup_parallel_bit_identical(rg_dataset, threads, proj):
    """The row-group execution plan must assemble EXACTLY the arrays the
    single-shot read produces — values, dtypes, and column set — over
    skewed (odd-sized) row groups, with and without a projection."""
    for fname in rg_dataset:
        base = sh.read_parquet_columns(fname, columns=proj)
        plan = sh.read_parquet_columns(
            fname, columns=proj, rowgroup_threads=threads
        )
        assert list(base.columns) == list(plan.columns)
        for k in base.columns:
            assert base[k].dtype == plan[k].dtype
            np.testing.assert_array_equal(base[k], plan[k])


def test_rowgroup_parallel_single_group_file(local_runtime, tmp_path):
    """A single-row-group file has nothing to parallelize: the plan
    degrades to the single-shot read, bit-identically."""
    filenames, _ = generate_data(
        num_rows=500,
        num_files=1,
        num_row_groups_per_file=1,
        max_row_group_skew=0.0,
        data_dir=str(tmp_path),
    )
    assert len(sh.file_row_group_sizes(filenames[0])) == 1
    base = sh.read_parquet_columns(filenames[0])
    plan = sh.read_parquet_columns(filenames[0], rowgroup_threads=4)
    for k in base.columns:
        np.testing.assert_array_equal(base[k], plan[k])


def test_rowgroup_selection_matches_slices(rg_dataset):
    """A row-group selection decodes exactly the concatenation of those
    groups' row ranges, in ascending group order."""
    fname = rg_dataset[0]
    sizes = sh.file_row_group_sizes(fname)
    assert len(sizes) >= 4
    offs = np.cumsum([0] + sizes)
    whole = sh.read_parquet_columns(fname)
    sel = [1, 3]
    got = sh.read_parquet_columns(
        fname, row_groups=sel, rowgroup_threads=2
    )
    for k in whole.columns:
        expect = np.concatenate(
            [whole[k][offs[g] : offs[g + 1]] for g in sel]
        )
        np.testing.assert_array_equal(got[k], expect)
    empty = sh.read_parquet_columns(
        fname, columns=["key"], row_groups=[]
    )
    assert empty.num_rows == 0
    assert empty["key"].dtype == whole["key"].dtype


def test_rowgroup_parallel_null_column_identical(local_runtime, tmp_path):
    """A column with nulls decodes to a promoted dtype (int64 ->
    float64 with NaN): the plan's per-stripe conversion uses the very
    calls the single-shot path uses, so the promoted result must be
    identical either way."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "nulls.parquet")
    table = pa.table(
        {
            "key": pa.array(list(range(100)), pa.int64()),
            "holey": pa.array(
                [None if i % 7 == 0 else i for i in range(100)],
                pa.int64(),
            ),
        }
    )
    with pq.ParquetWriter(path, table.schema) as w:
        for at in (0, 50):
            w.write_table(table.slice(at, 50), row_group_size=25)
    base = sh.read_parquet_columns(path)
    plan = sh.read_parquet_columns(path, rowgroup_threads=2)
    for k in base.columns:
        assert base[k].dtype == plan[k].dtype
        np.testing.assert_array_equal(base[k], plan[k])


def test_projection_missing_column_semantics(rg_dataset):
    """A typo'd explicit projection raises at the decode site (exactly
    as pq.read_table always did); ONLY the auto-appended audit key is
    tolerated-and-skipped — a keyless dataset must warn-and-skip in
    audit, not fail the map."""
    from ray_shuffling_data_loader_tpu.telemetry import audit as _audit

    with pytest.raises(ValueError, match="not in"):
        sh.read_parquet_columns(
            rg_dataset[0], columns=["labels", "no_such_column"]
        )
    saved = {
        k: os.environ.get(k) for k in ("RSDL_AUDIT", "RSDL_AUDIT_KEY")
    }
    os.environ["RSDL_AUDIT"] = "1"
    os.environ["RSDL_AUDIT_KEY"] = "no_such_column"
    _audit.refresh_from_env()
    try:
        got = sh.read_parquet_columns(
            rg_dataset[0], columns=["labels", "no_such_column"]
        )
        assert list(got.columns) == ["labels"]
        # ... but a projection selecting NOTHING still raises.
        with pytest.raises(ValueError, match="selects no columns"):
            sh.read_parquet_columns(
                rg_dataset[0], columns=["no_such_column"]
            )
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _audit.refresh_from_env()


def test_decode_rowgroup_threads_gate(monkeypatch):
    """RSDL_DECODE_ROWGROUPS parsing: unset/off = 1 (no thread ever),
    auto = fair share only when idle cores exist, integers forced."""
    monkeypatch.delenv("RSDL_DECODE_ROWGROUPS", raising=False)
    assert decode_rowgroup_threads(1) == 1
    monkeypatch.setenv("RSDL_DECODE_ROWGROUPS", "off")
    assert decode_rowgroup_threads(1) == 1
    monkeypatch.setenv("RSDL_DECODE_ROWGROUPS", "3")
    assert decode_rowgroup_threads(8) == 3
    monkeypatch.setenv("RSDL_DECODE_ROWGROUPS", "auto")
    cores = os.cpu_count() or 1
    # Saturated stage: auto declines.
    assert decode_rowgroup_threads(cores) == 1
    monkeypatch.setenv("RSDL_DECODE_ROWGROUPS", "on")
    assert decode_rowgroup_threads(cores) >= 2


# -- column pushdown --------------------------------------------------------


def test_pushdown_stream_and_counters(local_runtime, rg_dataset, monkeypatch):
    """An explicit ``columns=`` projection delivers exactly that set
    (plus the audit key when armed) and records pruned rows/bytes."""
    from ray_shuffling_data_loader_tpu.telemetry import metrics

    monkeypatch.setenv("RSDL_METRICS", "1")
    metrics.refresh_from_env()
    metrics.reset()
    try:
        consumer = _Collecting()
        # In-process decode so the counters land in THIS registry (the
        # lane also proves the spooled path end to end).
        refs = sh.shuffle_map(
            rg_dataset[0], 0, 2, epoch=0, seed=3,
            columns=["key", "labels"],
        )
        store = runtime.get_context().store
        got_cols = set(store.get_columns(refs[0]).columns)
        assert "key" in got_cols and "labels" in got_cols
        assert "embeddings_name0" not in got_cols
        store.free(refs)
        snap = metrics.registry.snapshot()
        assert _sum_metric(snap, "shuffle.decode_bytes_pruned") > 0
        assert _sum_metric(snap, "shuffle.decode_rowgroups") >= 1
        # The counters carry the map task's attribution (ISSUE 12);
        # the plan label follows the ambient env (the CI block leg
        # runs this very test under RSDL_SHUFFLE_PLAN=block).
        assert any(
            k.startswith("shuffle.decode_rowgroups{")
            and "schedule=mapreduce" in k
            and f"plan={shuffle_plan_label()}" in k
            for k in snap
        )
        # Full end-to-end projected shuffle still delivers every row.
        sh.shuffle(
            list(rg_dataset), consumer, num_epochs=1, num_reducers=3,
            num_trainers=1, seed=11, columns=["key", "labels"],
        )
        assert sorted(consumer.keys[(0, 0)]) == list(range(3000))
    finally:
        monkeypatch.delenv("RSDL_METRICS")
        metrics.refresh_from_env()
        metrics.reset()


def test_pushdown_declines_without_spec(rg_dataset, monkeypatch):
    """No explicit projection and no ``on`` override: full decode (the
    'decline when the spec is unknown' contract)."""
    monkeypatch.setenv("RSDL_DECODE_PUSHDOWN", "auto")
    assert sh._pushdown_columns(None, None) is None
    layout = {"batch": 8, "columns": ["key"]}
    # auto never derives from the layout alone...
    assert sh._pushdown_columns(layout, None) is None
    # ...on does; off never.
    monkeypatch.setenv("RSDL_DECODE_PUSHDOWN", "on")
    assert sh._pushdown_columns(layout, None) == ["key"]
    monkeypatch.setenv("RSDL_DECODE_PUSHDOWN", "off")
    assert sh._pushdown_columns(layout, ["key"]) is None


def test_stats_task_honors_projection(local_runtime, rg_dataset):
    """_dataset_stats_task must size the PROJECTED decoded footprint
    (satellite: the old estimate summed every schema column and
    mis-sized the store budget under pushdown)."""
    per_row_all, rows = sh._dataset_stats_task(list(rg_dataset), False)
    per_row_proj, rows2 = sh._dataset_stats_task(
        list(rg_dataset), False, ["key", "labels"]
    )
    assert rows == rows2 == 3000
    assert per_row_proj == pytest.approx(16.0)  # int64 key + f64 labels
    assert per_row_all > 10 * per_row_proj


# -- selective schedule (RINAS first cut) -----------------------------------


def test_selective_stream_identical(local_runtime, rg_dataset, monkeypatch):
    """RSDL_SELECTIVE_READS=on: every epoch runs the selective schedule
    (plan counts + row-group-selective reduce, no map materialization)
    and the delivered stream is IDENTICAL to the materialized path —
    same rows, same order, per (epoch, rank), fixed seed."""
    log_sel, log_mat = [], []
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "on")
    selective = _Collecting()
    sh.shuffle(
        list(rg_dataset), selective, num_epochs=2, num_reducers=4,
        num_trainers=2, seed=17, cache_decoded=False,
        schedule_log=log_sel,
    )
    monkeypatch.delenv("RSDL_SELECTIVE_READS")
    materialized = _Collecting()
    sh.shuffle(
        list(rg_dataset), materialized, num_epochs=2, num_reducers=4,
        num_trainers=2, seed=17, cache_decoded=False,
        schedule_log=log_mat,
    )
    assert [s for _, s in log_sel] == ["selective", "selective"]
    assert [s for _, s in log_mat] == ["mapreduce", "mapreduce"]
    assert dict(selective.keys) == dict(materialized.keys)
    assert dict(selective.done) == dict(materialized.done)


def test_selective_narrowed_stream_identical(
    local_runtime, rg_dataset, monkeypatch
):
    """Selective + narrow_to_32: the stream still matches the
    materialized path bit-for-bit (and under the audit-strict CI lane
    this proves the plan's NARROWED map digests reconcile against the
    narrowed reduce/deliver sides)."""
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "on")
    selective = _Collecting()
    sh.shuffle(
        list(rg_dataset), selective, num_epochs=1, num_reducers=4,
        num_trainers=1, seed=31, cache_decoded=False, narrow_to_32=True,
    )
    monkeypatch.delenv("RSDL_SELECTIVE_READS")
    materialized = _Collecting()
    sh.shuffle(
        list(rg_dataset), materialized, num_epochs=1, num_reducers=4,
        num_trainers=1, seed=31, cache_decoded=False, narrow_to_32=True,
    )
    assert dict(selective.keys) == dict(materialized.keys)


def test_selective_with_projection(local_runtime, rg_dataset, monkeypatch):
    """Selective reads compose with pushdown: projected columns only,
    exactly-once delivery intact."""
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "on")
    consumer = _Collecting()
    sh.shuffle(
        list(rg_dataset), consumer, num_epochs=1, num_reducers=5,
        num_trainers=1, seed=23, cache_decoded=False,
        columns=["key", "labels"],
    )
    assert sorted(consumer.keys[(0, 0)]) == list(range(3000))


# -- block-granular plan family (ISSUE 12) ----------------------------------


def test_shuffle_plan_spec_parsing(monkeypatch):
    """RSDL_SHUFFLE_PLAN parsing: rowwise default, block[:G], and a
    LOUD ValueError on anything malformed — the plan family decides the
    delivered stream, so a typo must never silently change it."""
    monkeypatch.delenv("RSDL_SHUFFLE_PLAN", raising=False)
    assert shuffle_plan_spec() == ("rowwise", 0)
    assert shuffle_plan_label() == "rowwise"
    monkeypatch.setenv("RSDL_SHUFFLE_PLAN", "block")
    assert shuffle_plan_spec() == ("block", 1)
    assert shuffle_plan_label() == "block:1"
    monkeypatch.setenv("RSDL_SHUFFLE_PLAN", "block:3")
    assert shuffle_plan_spec() == ("block", 3)
    assert shuffle_plan_label() == "block:3"
    for bad in ("block:0", "block:-1", "block:x", "banana"):
        monkeypatch.setenv("RSDL_SHUFFLE_PLAN", bad)
        with pytest.raises(ValueError, match="RSDL_SHUFFLE_PLAN"):
            shuffle_plan_spec()


def test_block_assignment_group_aligned(rg_dataset):
    """Under a block plan every row of a row group travels to ONE
    reducer, the assignment is deterministic per (seed, epoch, file),
    epochs re-deal, and the guards (missing filename, footer mismatch)
    raise loudly."""
    plan = ("block", 1)
    fname = rg_dataset[0]
    sizes = sh.file_row_group_sizes(fname)
    n = sum(sizes)
    a1 = sh._file_assignment(3, 1, 0, n, 4, fname, plan)
    a2 = sh._file_assignment(3, 1, 0, n, 4, fname, plan)
    np.testing.assert_array_equal(a1, a2)
    off = 0
    for s in sizes:
        assert len(set(a1[off:off + s].tolist())) == 1
        off += s
    a3 = sh._file_assignment(3, 2, 0, n, 4, fname, plan)
    assert not np.array_equal(a1, a3)
    with pytest.raises(ValueError, match="filename"):
        sh._file_assignment(3, 1, 0, n, 4, None, plan)
    with pytest.raises(ValueError, match="footer"):
        sh._file_assignment(3, 1, 0, n + 1, 4, fname, plan)


def test_block_granularity_blocks_groups(rg_dataset):
    """block:G deals CONSECUTIVE runs of G row groups to one reducer
    (the block is the unit of assignment, not the single group)."""
    fname = rg_dataset[0]
    sizes = sh.file_row_group_sizes(fname)
    owners = sh._group_owners(5, 0, 0, sizes, 3, 2)
    assert len(owners) == len(sizes)
    for b in range(0, len(sizes) - 1, 2):
        assert owners[b] == owners[b + 1]


def test_block_selections_disjoint_cover_once(rg_dataset):
    """The tentpole invariant: per-reducer row-group selections under a
    block plan are DISJOINT and cover every group exactly once — each
    group decodes once per epoch instead of ~R times — and per-file
    block counts are balanced to within one."""
    plan = ("block", 1)
    num_reducers = 4
    for i, fname in enumerate(rg_dataset):
        phys = len(sh.file_row_group_sizes(fname))
        sels = [
            sh.selective_file_selection(
                fname, i, r, num_reducers, 0, 9, plan
            )[0]
            for r in range(num_reducers)
        ]
        allg = np.concatenate(sels)
        assert len(allg) == phys
        assert len(np.unique(allg)) == phys
        lens = sorted(len(s) for s in sels)
        assert lens[-1] - lens[0] <= 1


def test_selective_auto_gate(monkeypatch):
    """RSDL_SELECTIVE_READS=auto engages only for prunable (block)
    plans and declines — with a reason — under rowwise."""
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "auto")
    monkeypatch.delenv("RSDL_SHUFFLE_PLAN", raising=False)
    engaged, reason = sh.selective_reads_decision()
    assert not engaged
    assert "declined" in reason and "rowwise" in reason
    monkeypatch.setenv("RSDL_SHUFFLE_PLAN", "block")
    engaged, reason = sh.selective_reads_decision()
    assert engaged
    assert "prunable" in reason
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "off")
    assert sh.selective_reads_decision() == (False, "off")
    # Forced on stays on regardless of plan family.
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "on")
    monkeypatch.delenv("RSDL_SHUFFLE_PLAN", raising=False)
    assert sh.selective_reads_decision()[0]


def test_selective_auto_declines_to_materialized(
    local_runtime, rg_dataset, monkeypatch
):
    """auto + rowwise runs the MATERIALIZED schedule instead of
    silently eating the R-fold selective re-read."""
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "auto")
    monkeypatch.delenv("RSDL_SHUFFLE_PLAN", raising=False)
    log = []
    consumer = _Collecting()
    sh.shuffle(
        list(rg_dataset), consumer, num_epochs=1, num_reducers=4,
        num_trainers=1, seed=3, cache_decoded=False, schedule_log=log,
    )
    assert [s for _, s in log] == ["mapreduce"]
    assert sorted(consumer.keys[(0, 0)]) == list(range(3000))


def test_block_selective_stream_matches_materialized(
    local_runtime, rg_dataset, monkeypatch
):
    """Selective and materialized deliver the SAME stream under the
    block plan family too (the _file_assignment seam is structural), and
    the stream is deterministic per seed."""
    monkeypatch.setenv("RSDL_SHUFFLE_PLAN", "block")
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "auto")
    log1 = []
    a = _Collecting()
    sh.shuffle(
        list(rg_dataset), a, num_epochs=2, num_reducers=4,
        num_trainers=2, seed=17, cache_decoded=False, schedule_log=log1,
    )
    assert [s for _, s in log1] == ["selective", "selective"]
    # Pin OFF (not unset) for the materialized control: under the CI
    # planner lane (RSDL_PLAN=auto) an unset knob is planner-owned and
    # would be planned right back to selective on this prunable shape.
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "off")
    log2 = []
    b = _Collecting()
    sh.shuffle(
        list(rg_dataset), b, num_epochs=2, num_reducers=4,
        num_trainers=2, seed=17, cache_decoded=False, schedule_log=log2,
    )
    assert [s for _, s in log2] == ["mapreduce", "mapreduce"]
    assert dict(a.keys) == dict(b.keys)
    assert dict(a.done) == dict(b.done)
    # Determinism per seed: a rerun delivers the identical stream.
    monkeypatch.setenv("RSDL_SELECTIVE_READS", "auto")
    c = _Collecting()
    sh.shuffle(
        list(rg_dataset), c, num_epochs=1, num_reducers=4,
        num_trainers=2, seed=17, cache_decoded=False,
    )
    assert c.keys[(0, 0)] == a.keys[(0, 0)]
    assert c.keys[(0, 1)] == a.keys[(0, 1)]


def test_block_selective_prunes_in_process(
    local_runtime, rg_dataset, monkeypatch
):
    """One in-process selective reduce under block:1 decodes ONLY its
    own groups: decode_rows_pruned engages (> 0), the rowgroup counter
    carries {schedule=selective, plan=block:1}, and groups decoded stay
    under the physical count (vs ~R x physical for rowwise)."""
    from ray_shuffling_data_loader_tpu.telemetry import metrics

    monkeypatch.setenv("RSDL_METRICS", "1")
    metrics.refresh_from_env()
    metrics.reset()
    try:
        plan = ("block", 1)
        out_ref = sh.shuffle_selective_reduce(
            0, 0, 5, list(rg_dataset), 4, plan=plan
        )
        store = runtime.get_context().store
        phys = sum(
            len(sh.file_row_group_sizes(f)) for f in rg_dataset
        )
        snap = metrics.registry.snapshot()
        groups = _sum_metric(snap, "shuffle.decode_rowgroups")
        assert 0 < groups <= phys
        assert _sum_metric(snap, "shuffle.decode_rows_pruned") > 0
        labeled = [
            k for k in snap
            if k.startswith("shuffle.decode_rowgroups{")
        ]
        assert labeled and all(
            "schedule=selective" in k and "plan=block:1" in k
            for k in labeled
        )
        expect_rows = sum(
            len(
                sh.selective_file_selection(
                    f, i, 0, 4, 0, 5, plan
                )[1]
            )
            for i, f in enumerate(rg_dataset)
        )
        cb = store.get_columns(out_ref)
        assert cb.num_rows == expect_rows
        del cb
        store.free(out_ref)
    finally:
        monkeypatch.delenv("RSDL_METRICS")
        metrics.refresh_from_env()
        metrics.reset()


# -- cross-epoch shared decode-cache tier -----------------------------------


def test_shared_cache_hit_across_runs(
    local_runtime, rg_dataset, monkeypatch, shared_cache_clean
):
    """Two consecutive shuffle() calls with the shared tier armed: the
    second starts cache-hot (epoch 0 goes straight to the index
    schedule) and delivers the same fixed-seed stream."""
    monkeypatch.setenv("RSDL_DECODE_CACHE_SHARED", "on")
    log1, log2 = [], []
    first = _Collecting()
    sh.shuffle(
        list(rg_dataset), first, num_epochs=2, num_reducers=4,
        num_trainers=1, seed=7, cache_decoded=True, schedule_log=log1,
    )
    assert dict(log1)[0] == "mapreduce"
    assert dict(log1)[1] == "index"
    assert len(sh._SHARED_CACHE) == len(rg_dataset)
    second = _Collecting()
    sh.shuffle(
        list(rg_dataset), second, num_epochs=2, num_reducers=4,
        num_trainers=1, seed=7, cache_decoded=True, schedule_log=log2,
    )
    assert dict(log2)[0] == "index"  # cache-hot from epoch 0
    assert dict(first.keys) == dict(second.keys)


def test_shared_cache_invalidation(
    local_runtime, rg_dataset, monkeypatch, shared_cache_clean
):
    """A shed segment (evictor drop, session cleanup) must never be
    handed out: the registry validates against the store and the next
    run re-decodes — degraded, never broken."""
    monkeypatch.setenv("RSDL_DECODE_CACHE_SHARED", "on")
    warm = _Collecting()
    sh.shuffle(
        list(rg_dataset), warm, num_epochs=2, num_reducers=3,
        num_trainers=1, seed=7, cache_decoded=True,
    )
    store = runtime.get_context().store
    refs = list(sh._SHARED_CACHE.values())
    assert refs and all(store.exists(r) for r in refs)
    store.free(refs)  # simulate the evictor's drop rung
    log = []
    cold = _Collecting()
    sh.shuffle(
        list(rg_dataset), cold, num_epochs=1, num_reducers=3,
        num_trainers=1, seed=7, cache_decoded=True, schedule_log=log,
    )
    assert dict(log)[0] == "mapreduce"  # re-decoded, no dangling ref
    assert sorted(cold.keys[(0, 0)]) == list(range(3000))
    assert dict(cold.keys) == {
        k: v for k, v in warm.keys.items() if k[0] == 0
    }


def test_shared_cache_off_by_default(
    local_runtime, rg_dataset, shared_cache_clean
):
    """Gates unset: per-run cache semantics untouched — no registry
    entry survives the run (zero-overhead contract)."""
    os.environ.pop("RSDL_DECODE_CACHE_SHARED", None)
    consumer = _Collecting()
    sh.shuffle(
        list(rg_dataset), consumer, num_epochs=2, num_reducers=3,
        num_trainers=1, seed=5, cache_decoded=True,
    )
    assert sh._SHARED_CACHE == {}


# -- zero-overhead off ------------------------------------------------------


@pytest.mark.slow
def test_zero_overhead_when_gates_unset(tmp_path):
    """Fresh interpreter, every decode-plane gate unset: a real shuffle
    run spawns no decode threads, imports no capacity ledger, registers
    nothing in the shared tier, and the metrics spool stays absent (so
    no ledger ``touch`` records can exist)."""
    code = """
import os, sys, threading
for k in list(os.environ):
    if k.startswith("RSDL_"):
        del os.environ[k]
os.environ["RSDL_SHM_DIR"] = r"%(shm)s"
os.environ["JAX_PLATFORMS"] = "cpu"

def main():
    import importlib
    from ray_shuffling_data_loader_tpu import runtime
    from ray_shuffling_data_loader_tpu.data_generation import generate_data
    sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
    runtime.init(num_workers=2)
    files, _ = generate_data(600, 2, 3, 0.0, r"%(data)s")
    class C(sh.BatchConsumer):
        def consume(self, rank, epoch, batches):
            runtime.get_context().store.free(list(batches))
        def producer_done(self, rank, epoch): pass
        def wait_until_ready(self, epoch): pass
        def wait_until_all_epochs_done(self): pass
    sh.shuffle(files, C(), num_epochs=2, num_reducers=2,
               num_trainers=1, seed=1, cache_decoded=True)
    assert "ray_shuffling_data_loader_tpu.telemetry.capacity" \\
        not in sys.modules, "capacity ledger imported with gates unset"
    assert sh._SHARED_CACHE == {}, "shared tier armed with gates unset"
    assert not any(
        t.name.startswith("rsdl-decode") for t in threading.enumerate()
    ), "decode threads with gates unset"
    from ray_shuffling_data_loader_tpu.utils import (
        decode_rowgroup_threads,
    )
    assert decode_rowgroup_threads(1) == 1
    runtime.shutdown()
    print("ZERO-OVERHEAD-OK")

if __name__ == "__main__":
    main()
""" % {"shm": str(tmp_path / "shm"), "data": str(tmp_path / "data")}
    script = tmp_path / "zero_overhead.py"
    script.write_text(code)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert "ZERO-OVERHEAD-OK" in out.stdout
