"""Property-based re-batching invariants (hypothesis).

The carry-buffer re-batcher (``dataset.ShufflingDataset.__iter__``,
reference ``dataset.py:118-182``) must, for ANY partitioning of the
shuffled stream into reducer outputs and ANY batch size:

* yield batches of exactly ``batch_size`` rows (except an optional final
  partial, dropped under ``drop_last``);
* preserve the stream's row ORDER (re-batching is a reshape, not a
  shuffle);
* lose and duplicate nothing;
* honor ``skip_batches`` resume (the yielded suffix equals the full
  stream minus the first k batches).

Randomized structure generation finds the boundary cases enumerated
tests miss (empty reducer outputs, outputs smaller than the buffer
top-up, exact-multiple boundaries) — the reference's tail-drop bug
(``dataset.py:160-168``) is exactly the kind of case this sweeps for.
The suite drives the PRODUCTION ``CarryRebatcher`` — the object
``ShufflingDataset.__iter__`` itself feeds — so the invariants hold for
the real iterator, not a hand-copied mirror.
"""

import os

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property sweep needs the hypothesis package"
)
from hypothesis import given, settings, strategies as st

# Depth profiles: default 200 examples; HYPOTHESIS_PROFILE=deep (or the
# soak runner) sweeps 5000 per property.
settings.register_profile("default", max_examples=200, deadline=None)
settings.register_profile("deep", max_examples=5000, deadline=None)
settings.load_profile(os.environ.get("HYPOTHESIS_PROFILE", "default"))

from ray_shuffling_data_loader_tpu.dataset import CarryRebatcher
from ray_shuffling_data_loader_tpu.runtime import ColumnBatch


def _rebatch(outputs, batch_size, drop_last=False, skip_batches=0):
    """Drive the PRODUCTION re-batcher (the same CarryRebatcher
    ShufflingDataset.__iter__ feeds with the real stream) over in-memory
    reducer outputs."""
    rb = CarryRebatcher(batch_size, skip_batches)
    out = []
    for cb in outputs:
        out.extend(rb.feed(cb))
    final = rb.finish(drop_last)
    if final is not None:
        out.append(final)
    return out


@st.composite
def stream_partition(draw):
    """A random row stream cut into random reducer-output sizes."""
    total = draw(st.integers(min_value=0, max_value=400))
    sizes = []
    left = total
    while left > 0:
        s = draw(st.integers(min_value=0, max_value=left))
        sizes.append(s)
        left -= s
    # Sprinkle empty outputs anywhere (reducers can legally emit none).
    for _ in range(draw(st.integers(0, 2))):
        sizes.insert(draw(st.integers(0, len(sizes))) if sizes else 0, 0)
    batch_size = draw(st.integers(min_value=1, max_value=64))
    return sizes, batch_size


def _outputs(sizes):
    rows = np.arange(sum(sizes), dtype=np.int64)
    outputs, at = [], 0
    for s in sizes:
        outputs.append(ColumnBatch({"key": rows[at : at + s]}))
        at += s
    return rows, outputs


@given(stream_partition(), st.booleans())
def test_rebatch_exact_sizes_order_exactly_once(case, drop_last):
    sizes, batch_size = case
    rows, outputs = _outputs(sizes)
    batches = _rebatch(outputs, batch_size, drop_last=drop_last)
    n = len(rows)
    full, tail = divmod(n, batch_size)
    assert len(batches) == full + (0 if drop_last or tail == 0 else 1)
    for b in batches[:full]:
        assert b.num_rows == batch_size
    got = np.concatenate(
        [np.asarray(b.columns["key"]) for b in batches]
    ) if batches else np.array([], dtype=np.int64)
    expect = rows if not drop_last else rows[: full * batch_size]
    assert np.array_equal(got, expect), "order / exactly-once violated"


@given(stream_partition(), st.integers(min_value=0, max_value=12))
def test_rebatch_skip_batches_is_suffix(case, skip):
    sizes, batch_size = case
    rows, outputs = _outputs(sizes)
    all_batches = _rebatch(outputs, batch_size)
    resumed = _rebatch(outputs, batch_size, skip_batches=skip)
    # Skipping k batches yields the same stream minus the first k
    # (the final partial counts as a batch in yield order too).
    k = min(skip, len(all_batches))
    expect = [np.asarray(b.columns["key"]) for b in all_batches[k:]]
    got = [np.asarray(b.columns["key"]) for b in resumed]
    assert len(got) == len(expect)
    for g, e in zip(got, expect):
        assert np.array_equal(g, e)
