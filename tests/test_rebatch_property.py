"""Property-based re-batching invariants (hypothesis).

The carry-buffer re-batcher (``dataset.ShufflingDataset.__iter__``,
reference ``dataset.py:118-182``) must, for ANY partitioning of the
shuffled stream into reducer outputs and ANY batch size:

* yield batches of exactly ``batch_size`` rows (except an optional final
  partial, dropped under ``drop_last``);
* preserve the stream's row ORDER (re-batching is a reshape, not a
  shuffle);
* lose and duplicate nothing;
* honor ``skip_batches`` resume (the yielded suffix equals the full
  stream minus the first k batches).

Randomized structure generation finds the boundary cases enumerated
tests miss (empty reducer outputs, outputs smaller than the buffer
top-up, exact-multiple boundaries) — the reference's tail-drop bug
(``dataset.py:160-168``) is exactly the kind of case this sweeps for.
The queue/store machinery is bypassed on purpose: the property under
test is the pure re-batching algebra, driven through the same
``ColumnBatch.concat``/``slice`` operations the real iterator uses.
"""

import numpy as np
from hypothesis import given, settings, strategies as st

from ray_shuffling_data_loader_tpu.runtime import ColumnBatch


def _rebatch(outputs, batch_size, drop_last=False, skip_batches=0):
    """The iterator's carry-buffer algebra, isolated — an exact mirror of
    ``dataset.py:210-251``'s loop over in-memory reducer outputs."""
    buf = None
    to_skip = skip_batches
    out = []
    for cb in outputs:
        offset = batch_size - (buf.num_rows if buf else 0)
        buf = ColumnBatch.concat([buf, cb.slice(0, offset)])
        if buf.num_rows == batch_size:
            if to_skip > 0:
                to_skip -= 1
            else:
                out.append(buf)
            buf = None
        start = min(offset, cb.num_rows)
        num_full = (cb.num_rows - start) // batch_size
        num_skipped = min(to_skip, num_full)
        to_skip -= num_skipped
        for i in range(num_skipped, num_full):
            lo = start + i * batch_size
            out.append(cb.slice(lo, lo + batch_size))
        tail = start + num_full * batch_size
        if tail < cb.num_rows:
            buf = cb.slice(tail, cb.num_rows)
    if buf is not None and buf.num_rows > 0 and not drop_last:
        if to_skip > 0:
            to_skip -= 1
        else:
            out.append(buf)
    return out


@st.composite
def stream_partition(draw):
    """A random row stream cut into random reducer-output sizes."""
    total = draw(st.integers(min_value=0, max_value=400))
    sizes = []
    left = total
    while left > 0:
        s = draw(st.integers(min_value=0, max_value=left))
        sizes.append(s)
        left -= s
    # Sprinkle empty outputs anywhere (reducers can legally emit none).
    for _ in range(draw(st.integers(0, 2))):
        sizes.insert(draw(st.integers(0, len(sizes))) if sizes else 0, 0)
    batch_size = draw(st.integers(min_value=1, max_value=64))
    return sizes, batch_size


def _outputs(sizes):
    rows = np.arange(sum(sizes), dtype=np.int64)
    outputs, at = [], 0
    for s in sizes:
        outputs.append(ColumnBatch({"key": rows[at : at + s]}))
        at += s
    return rows, outputs


@given(stream_partition(), st.booleans())
@settings(max_examples=200, deadline=None)
def test_rebatch_exact_sizes_order_exactly_once(case, drop_last):
    sizes, batch_size = case
    rows, outputs = _outputs(sizes)
    batches = _rebatch(outputs, batch_size, drop_last=drop_last)
    n = len(rows)
    full, tail = divmod(n, batch_size)
    assert len(batches) == full + (0 if drop_last or tail == 0 else 1)
    for b in batches[:full]:
        assert b.num_rows == batch_size
    got = np.concatenate(
        [np.asarray(b.columns["key"]) for b in batches]
    ) if batches else np.array([], dtype=np.int64)
    expect = rows if not drop_last else rows[: full * batch_size]
    assert np.array_equal(got, expect), "order / exactly-once violated"


@given(stream_partition(), st.integers(min_value=0, max_value=12))
@settings(max_examples=200, deadline=None)
def test_rebatch_skip_batches_is_suffix(case, skip):
    sizes, batch_size = case
    rows, outputs = _outputs(sizes)
    all_batches = _rebatch(outputs, batch_size)
    resumed = _rebatch(outputs, batch_size, skip_batches=skip)
    # Skipping k batches yields the same stream minus the first k
    # (the final partial counts as a batch in yield order too).
    k = min(skip, len(all_batches))
    expect = [np.asarray(b.columns["key"]) for b in all_batches[k:]]
    got = [np.asarray(b.columns["key"]) for b in resumed]
    assert len(got) == len(expect)
    for g, e in zip(got, expect):
        assert np.array_equal(g, e)
