"""Per-op phase profiler tests (ISSUE 5): the zero-overhead-off
contract, phase timers summing to the stage wall time, and the metrics/
trace wiring the shuffle stages feed."""

import time

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu.telemetry import metrics, phases, trace


@pytest.fixture
def telemetry_off(monkeypatch):
    monkeypatch.delenv("RSDL_METRICS", raising=False)
    monkeypatch.delenv("RSDL_TRACE", raising=False)
    metrics.refresh_from_env()
    trace.refresh_from_env()
    yield
    metrics.refresh_from_env()
    trace.refresh_from_env()


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("RSDL_METRICS", "1")
    monkeypatch.delenv("RSDL_TRACE", raising=False)
    metrics.refresh_from_env()
    trace.refresh_from_env()
    yield
    metrics.reset()
    metrics.refresh_from_env()
    trace.refresh_from_env()


def test_disabled_returns_shared_noop(telemetry_off):
    """Both halves off -> one shared no-op singleton, nothing allocated,
    nothing registered (the zero-overhead contract)."""
    before = set(metrics.registry.snapshot())
    p1 = phases.stage_profiler("map", epoch=0)
    p2 = phases.stage_profiler("reduce")
    assert p1 is p2 is phases._NULL
    with p1.phase("decode") as ph:
        ph.add_bytes(123)
    assert p1.totals() == {}
    assert p1.wall() == 0.0
    assert set(metrics.registry.snapshot()) == before


def test_phase_timers_sum_to_stage_wall(metrics_on):
    """The recorded phase durations must account for (approximately) the
    whole stage wall time when the stage body is fully phased."""
    prof = phases.stage_profiler("map", epoch=1, file=0)
    assert isinstance(prof, phases.StageProfiler)
    t0 = time.perf_counter()
    with prof.phase("decode") as ph:
        time.sleep(0.02)
        ph.add_bytes(1000)
    with prof.phase("partition-scatter", nbytes=2000):
        time.sleep(0.03)
    wall = time.perf_counter() - t0
    totals = prof.totals()
    assert set(totals) == {"decode", "partition-scatter"}
    assert totals["decode"] >= 0.02
    assert totals["partition-scatter"] >= 0.03
    # Phases cover the stage: the sum tracks the wall clock to within
    # the inter-phase bookkeeping (generous bound for a loaded CI host).
    assert abs(prof.wall() - wall) < 0.02
    assert prof.wall() == pytest.approx(sum(totals.values()))


def test_phase_metrics_series(metrics_on):
    """Each phase lands one histogram observation and (when bytes are
    reported) a byte-counter increment under the documented keys."""
    prof = phases.stage_profiler("reduce", epoch=0, reducer=3)
    with prof.phase("gather", nbytes=500):
        pass
    with prof.phase("gather") as ph:
        ph.add_bytes(300)
    with prof.phase("publish"):
        pass
    snap = metrics.registry.snapshot()
    hkey = metrics.format_key(
        "shuffle.phase_seconds", {"phase": "gather", "stage": "reduce"}
    )
    assert snap[f"{hkey}_count"] == 2
    bkey = metrics.format_key(
        "shuffle.phase_bytes", {"phase": "gather", "stage": "reduce"}
    )
    assert snap[bkey] == 800
    pkey = metrics.format_key(
        "shuffle.phase_seconds", {"phase": "publish", "stage": "reduce"}
    )
    assert snap[f"{pkey}_count"] == 1
    # No bytes reported for publish -> no byte counter for it.
    assert (
        metrics.format_key(
            "shuffle.phase_bytes", {"phase": "publish", "stage": "reduce"}
        )
        not in snap
    )


def test_repeated_phase_accumulates(metrics_on):
    """A phase entered per-window (the overlapped reduce) sums in
    totals() and observes once per entry in the histogram."""
    prof = phases.stage_profiler("reduce", epoch=0, reducer=0)
    for _ in range(4):
        with prof.phase("window-fetch", nbytes=10):
            pass
    totals = prof.totals()
    assert list(totals) == ["window-fetch"]
    snap = metrics.registry.snapshot()
    hkey = metrics.format_key(
        "shuffle.phase_seconds",
        {"phase": "window-fetch", "stage": "reduce"},
    )
    assert snap[f"{hkey}_count"] == 4


def test_shuffle_map_records_phases(local_runtime, metrics_on, tmp_path):
    """End to end: a real shuffle_map run in-process registers the map
    phase series (decode:arrow, partition-scatter, publish — the
    monolithic decode phase split into decode:io/arrow/narrow,
    ISSUE 11)."""
    from ray_shuffling_data_loader_tpu.data_generation import generate_data
    from ray_shuffling_data_loader_tpu.shuffle import shuffle_map

    filenames, _ = generate_data(
        num_rows=400,
        num_files=1,
        num_row_groups_per_file=1,
        max_row_group_skew=0.0,
        data_dir=str(tmp_path),
    )
    ctx = local_runtime
    refs = shuffle_map(filenames[0], 0, 2, epoch=0, seed=1)
    try:
        snap = metrics.registry.snapshot()
        for phase in ("decode:arrow", "partition-scatter", "publish"):
            key = metrics.format_key(
                "shuffle.phase_seconds", {"phase": phase, "stage": "map"}
            )
            assert snap[f"{key}_count"] >= 1, phase
        dkey = metrics.format_key(
            "shuffle.phase_bytes", {"phase": "decode:arrow", "stage": "map"}
        )
        assert snap[dkey] > 0
    finally:
        ctx.store.free(refs)


def test_overlapped_reduce_matches_fused(local_runtime, monkeypatch, tmp_path):
    """RSDL_REDUCE_FETCH_OVERLAP=on (forced, local refs) must produce a
    bit-identical reducer output to the fused concat-take path — the
    overlap is a scheduling change, never a data change."""
    from ray_shuffling_data_loader_tpu.shuffle import (
        shuffle_map,
        shuffle_reduce,
    )
    from ray_shuffling_data_loader_tpu.data_generation import generate_data

    filenames, _ = generate_data(
        num_rows=1200,
        num_files=3,
        num_row_groups_per_file=1,
        max_row_group_skew=0.0,
        data_dir=str(tmp_path),
    )
    store = local_runtime.store
    num_reducers = 4

    def _reduce_all(mode):
        monkeypatch.setenv("RSDL_REDUCE_FETCH_OVERLAP", mode)
        per_file = [
            shuffle_map(f, i, num_reducers, epoch=2, seed=9)
            for i, f in enumerate(filenames)
        ]
        outs = []
        for r in range(num_reducers):
            out_ref = shuffle_reduce(
                r, epoch=2, seed=9,
                part_refs=[refs[r] for refs in per_file],
            )
            outs.append(
                {
                    k: np.array(v)
                    for k, v in store.get_columns(out_ref).items()
                }
            )
            store.free(out_ref)
        for refs in per_file:
            store.free(refs)
        return outs

    fused = _reduce_all("off")
    overlapped = _reduce_all("on")
    for a, b in zip(fused, overlapped):
        assert sorted(a) == sorted(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
