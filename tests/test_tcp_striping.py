"""Multi-stream striped zero-copy fetch tests (ISSUE 6, tentpole c).

The ``RSDL_TCP_STREAMS`` plane splits each segment fetch by byte range
across persistent authed connections, landing every stripe in a disjoint
window of one destination mapping. The contract under test:

* server-side stripe slicing tiles the exact single-stream serialization
  (no byte ever duplicated or dropped);
* the full striped client path over real authed TCP produces a
  destination file byte-identical to the single-stream fetch;
* a tampered/corrupt stripe surfaces as the existing retry-safe error
  class (``ActorDiedError``/``ConnectionError``), never a silent
  short read;
* the knob defaults off (1 stream = pre-striping wire behavior).
"""

import concurrent.futures
import mmap as mmap_mod
import os
import tempfile
import threading

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu.runtime import transport
from ray_shuffling_data_loader_tpu.runtime.actor import (
    ActorDiedError,
    spawn_actor,
)
from ray_shuffling_data_loader_tpu.runtime.cluster import (
    StoreServer,
    _slice_buffers,
    fetch_vec_striped,
)
from ray_shuffling_data_loader_tpu.runtime.store import (
    ObjectStore,
    serialize_columns_vectored,
)

rng = np.random.default_rng(7)


def test_tcp_streams_knob_default_off(monkeypatch):
    monkeypatch.delenv(transport.ENV_TCP_STREAMS, raising=False)
    transport.refresh_tcp_streams_from_env()
    assert transport.tcp_streams() == 1
    monkeypatch.setenv(transport.ENV_TCP_STREAMS, "3")
    transport.refresh_tcp_streams_from_env()
    assert transport.tcp_streams() == 3
    # clamped to [1, 16]; junk falls back to 1
    monkeypatch.setenv(transport.ENV_TCP_STREAMS, "99")
    transport.refresh_tcp_streams_from_env()
    assert transport.tcp_streams() == 16
    monkeypatch.setenv(transport.ENV_TCP_STREAMS, "junk")
    transport.refresh_tcp_streams_from_env()
    assert transport.tcp_streams() == 1
    monkeypatch.delenv(transport.ENV_TCP_STREAMS, raising=False)
    transport.refresh_tcp_streams_from_env()


def test_slice_buffers_tiles_exactly():
    """Stripe ranges must tile the serialization: concatenating every
    stripe's buffers reproduces the unstriped byte string for any stream
    count, including ranges that split a buffer mid-way."""
    cols = {
        "a": np.arange(777, dtype=np.int32),
        "b": rng.random((777, 2)),
        "c": (np.arange(777) % 2).astype(np.bool_),
    }
    total, bufs = serialize_columns_vectored(cols)
    whole = b"".join(bytes(memoryview(b).cast("B")) for b in bufs)
    assert len(whole) == total
    for n in (1, 2, 3, 7, 16):
        parts = []
        for i in range(n):
            lo, hi = i * total // n, (i + 1) * total // n
            parts.append(
                b"".join(
                    bytes(memoryview(b).cast("B"))
                    for b in _slice_buffers(bufs, lo, hi)
                )
            )
            assert sum(len(p) for p in parts[-1:]) == hi - lo
        assert b"".join(parts) == whole, n


@pytest.fixture(scope="module")
def store_server():
    """A real StoreServer actor on authed loopback TCP, plus a local
    store holding one published multi-column segment."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    token_prev = os.environ.get("RSDL_CLUSTER_TOKEN")
    os.environ["RSDL_CLUSTER_TOKEN"] = "striping-test-secret"
    shm = tempfile.mkdtemp(prefix="rsdl-stripe-shm-")
    rt = tempfile.mkdtemp(prefix="rsdl-stripe-rt-")
    store = ObjectStore("stripesess", shm_dir=shm)
    cols = {
        "a": rng.integers(0, 1 << 30, size=50_000),
        "b": rng.random(50_000).astype(np.float32),
    }
    ref = store.put_columns(cols)
    handle = spawn_actor(StoreServer, shm, runtime_dir=rt, host="127.0.0.1")
    try:
        yield handle, store, ref, shm
    finally:
        handle.terminate()
        store.cleanup()
        if token_prev is None:
            os.environ.pop("RSDL_CLUSTER_TOKEN", None)
        else:
            os.environ["RSDL_CLUSTER_TOKEN"] = token_prev


def _striped_to_file(handle, object_id, rows, shm, n_streams, pool):
    """Run fetch_vec_striped with the store's real allocator shape
    (mmapped destination file); returns the file's bytes."""
    dst = os.path.join(shm, f"dst-{n_streams}-{threading.get_ident()}")
    state = {}

    def alloc(n):
        fd = os.open(dst, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, max(n, 1))
            state["mm"] = mmap_mod.mmap(fd, max(n, 1))
        finally:
            os.close(fd)
        return state["mm"]

    try:
        fetch_vec_striped(handle, object_id, rows, alloc, n_streams, pool)
        return bytes(state["mm"])
    finally:
        if "mm" in state:
            state["mm"].close()
        try:
            os.unlink(dst)
        except FileNotFoundError:
            pass


def test_striped_fetch_byte_identical(store_server):
    handle, store, ref, shm = store_server
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=4)
    single = handle.call("fetch", ref.object_id, None)
    for n in (2, 3, 4):
        got = _striped_to_file(handle, ref.object_id, None, shm, n, pool)
        assert got == single, f"{n} streams"
    # row-window refs stripe the re-serialized window, same equality
    win = handle.call("fetch", ref.object_id, (100, 9000))
    got = _striped_to_file(handle, ref.object_id, (100, 9000), shm, 3, pool)
    assert got == win
    pool.shutdown()


def test_striped_fetch_more_streams_than_bytes(store_server):
    """total < n_streams leaves some stripes empty; the fetch must still
    assemble the exact bytes (tiny segment, 16 streams)."""
    handle, store, ref, shm = store_server
    tiny = store.put_columns({"t": np.arange(2, dtype=np.int8)})
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=16)
    single = handle.call("fetch", tiny.object_id, None)
    got = _striped_to_file(handle, tiny.object_id, None, shm, 16, pool)
    assert got == single
    pool.shutdown()
    store.free(tiny)


def test_striped_fetch_corrupt_stripe_raises_retry_safe(store_server):
    """A stripe whose reply meta is inconsistent (tampered length/total)
    must surface as the existing retry-safe error class — the same
    ActorDiedError/ConnectionError ladder the single-stream fetch dies
    with — and must not leave a destination mapping behind."""
    handle, store, ref, shm = store_server

    class TamperedHandle:
        """Proxy corrupting stripe 1's reply meta before the allocator
        sees it (a wire-level tamper would fail the same validation —
        the stripe byte-range no longer matches the payload length)."""

        def call_vectored(self, method, object_id, rows, stripe, into):
            def tampered(nbytes, meta):
                if stripe[0] == 1:
                    meta = dict(meta, nbytes=int(meta["nbytes"]) + 64)
                return into(nbytes, meta)

            tampered.wants_meta = True
            return handle.call_vectored(
                method, object_id, rows, stripe=stripe, into=tampered
            )

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=2)
    state = {}

    def alloc(n):
        state["mm"] = mmap_mod.mmap(-1, max(n, 1))
        return state["mm"]

    with pytest.raises((ActorDiedError, ConnectionError)):
        fetch_vec_striped(
            TamperedHandle(), ref.object_id, None, alloc, 2, pool
        )
    if "mm" in state:
        state["mm"].close()
    pool.shutdown()


def test_striped_fetch_wrong_token_raises_retry_safe(store_server, monkeypatch):
    """HMAC tamper on a stripe connection: the server drops the peer
    before any frame is served and the striped fetch dies with the
    retry-safe ActorDiedError (fresh pool so connections are new)."""
    handle, store, ref, shm = store_server
    monkeypatch.setenv("RSDL_CLUSTER_TOKEN", "WRONG-secret")
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=2)
    state = {}

    def alloc(n):
        state["mm"] = mmap_mod.mmap(-1, max(n, 1))
        return state["mm"]

    with pytest.raises((ActorDiedError, ConnectionError)):
        fetch_vec_striped(handle, ref.object_id, None, alloc, 2, pool)
    if "mm" in state:
        state["mm"].close()
    pool.shutdown()
