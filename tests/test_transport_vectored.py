"""Vectored (zero-copy) framing tests for the transport plane (ISSUE 5):
scatter-gather round-trips over real sockets, recv_into a caller-owned
buffer, interleaving with plain frames, the env gate's default-off
contract, and HMAC auth gating the vectored path like every other frame.
"""

import asyncio
import os
import socket
import threading

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu.runtime import transport
from ray_shuffling_data_loader_tpu.runtime.store import (
    serialize_columns,
    serialize_columns_vectored,
)


def _conn_pair():
    """Two Connection objects over a socketpair (no handshake — unix
    sockets don't auth)."""
    a, b = socket.socketpair()
    ca = transport.Connection.__new__(transport.Connection)
    ca.address = ("test", "a")
    ca.sock = a
    cb = transport.Connection.__new__(transport.Connection)
    cb.address = ("test", "b")
    cb.sock = b
    return ca, cb


def test_vectored_roundtrip_socketpair():
    ca, cb = _conn_pair()
    payloads = [b"hello-", np.arange(1000, dtype=np.int64), b"-tail"]
    expect = b"hello-" + np.arange(1000, dtype=np.int64).tobytes() + b"-tail"
    sender = threading.Thread(
        target=ca.send_vectored, args=(("meta", 42), payloads)
    )
    sender.start()
    obj, view = cb.recv_frame()
    sender.join()
    assert obj == ("meta", 42)
    assert bytes(view) == expect
    ca.close()
    cb.close()


def test_vectored_recv_into_caller_buffer():
    """The payload must land in the allocator's buffer (the store mmaps
    the destination cache file through exactly this hook)."""
    ca, cb = _conn_pair()
    data = np.random.default_rng(0).integers(0, 255, 4096).astype(np.uint8)
    got = {}

    def alloc(n):
        got["buf"] = bytearray(n)
        return got["buf"]

    sender = threading.Thread(
        target=ca.send_vectored, args=("m", [data])
    )
    sender.start()
    obj, view = cb.recv_frame(into=alloc)
    sender.join()
    assert obj == "m"
    assert bytes(got["buf"]) == data.tobytes()
    assert view.obj is not None  # a view over the caller's buffer
    ca.close()
    cb.close()


def test_vectored_recv_meta_aware_allocator():
    """An allocator marked ``wants_meta`` receives the frame's header
    object alongside the byte count — the striped fetch positions its
    destination window from the stripe range carried there."""
    ca, cb = _conn_pair()
    seen = {}

    def alloc(n, obj):
        seen["n"], seen["obj"] = n, obj
        return bytearray(n)

    alloc.wants_meta = True
    sender = threading.Thread(
        target=ca.send_vectored,
        args=(({"stripe": [3, 7]}, "x"), [b"abcd"]),
    )
    sender.start()
    obj, view = cb.recv_frame(into=alloc)
    sender.join()
    assert seen == {"n": 4, "obj": ({"stripe": [3, 7]}, "x")}
    assert bytes(view) == b"abcd"
    ca.close()
    cb.close()


def test_plain_and_vectored_frames_interleave():
    ca, cb = _conn_pair()

    def _send():
        ca.send({"plain": 1})
        ca.send_vectored("vec", [b"abc"])
        ca.send({"plain": 2})

    sender = threading.Thread(target=_send)
    sender.start()
    assert cb.recv() == {"plain": 1}
    obj, view = cb.recv_frame()
    assert obj == "vec" and bytes(view) == b"abc"
    assert cb.recv() == {"plain": 2}
    sender.join()
    ca.close()
    cb.close()


def test_vectored_recv_failure_releases_buffer():
    """Peer dies mid-payload: the recoverable ConnectionError must
    propagate AND the caller must be able to close the destination
    buffer's mmap immediately — a recv view surviving into the
    traceback would turn the cleanup close() into BufferError and
    break the fetch retry ladder (store._materialize_remote)."""
    import mmap as mmap_mod
    import tempfile

    ca, cb = _conn_pair()
    # Hand-craft a vectored header promising more payload than is sent,
    # then close the sender mid-payload.
    header = transport.dumps(("meta", [1 << 20]))
    ca.sock.sendall(
        transport._LEN.pack(transport._VEC_FLAG | len(header))
        + header
        + b"short"
    )
    ca.close()

    with tempfile.TemporaryFile() as f:
        f.truncate(1 << 20)
        mm = mmap_mod.mmap(f.fileno(), 1 << 20)
        try:
            with pytest.raises(ConnectionError):
                cb.recv_frame(into=lambda n: mm)
            mm.close()  # must NOT raise BufferError
        finally:
            if not mm.closed:
                mm.close()
    cb.close()


def test_serialize_columns_vectored_matches_bytes():
    """The scatter-gather list must concatenate to the exact byte string
    the legacy serializer produces — the reader's cache file is identical
    either way (multi-column with alignment gaps + a 2-D column)."""
    cols = {
        "a": np.arange(7, dtype=np.int32),          # 28 B -> 36 B gap pad
        "b": np.arange(14, dtype=np.float64).reshape(7, 2),
        "c": (np.arange(7) % 2).astype(np.bool_),   # odd width tail
    }
    legacy = serialize_columns(cols)
    total, bufs = serialize_columns_vectored(cols)
    joined = b"".join(bytes(memoryview(b).cast("B")) for b in bufs)
    assert total == len(legacy)
    assert joined == legacy


def test_zerocopy_gate_default_off(monkeypatch):
    monkeypatch.delenv(transport.ENV_ZEROCOPY, raising=False)
    transport.refresh_zerocopy_from_env()
    assert transport.zerocopy_enabled() is False
    monkeypatch.setenv(transport.ENV_ZEROCOPY, "1")
    transport.refresh_zerocopy_from_env()
    assert transport.zerocopy_enabled() is True
    monkeypatch.delenv(transport.ENV_ZEROCOPY, raising=False)
    transport.refresh_zerocopy_from_env()


class _TcpVecServer:
    """A minimal asyncio TCP server (token-authed via transport.start_server)
    whose handler answers each plain request frame with one vectored reply
    — the StoreServer fetch_vec wire shape without the actor machinery."""

    def __init__(self):
        self._loop = None
        self._started = threading.Event()
        self.port = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._started.wait(10), "server failed to start"

    def _run(self):
        async def handler(reader, writer):
            try:
                while True:
                    req = await transport.read_frame(reader)
                    transport.write_frame_vectored(
                        writer,
                        ("echo", req),
                        [b"PAYLOAD:", np.arange(64, dtype=np.int32)],
                    )
                    await writer.drain()
            except (
                asyncio.IncompleteReadError,
                ConnectionError,
                OSError,
            ):
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def main():
            server = await transport.start_server(
                ("tcp", "127.0.0.1", 0), handler
            )
            self.port = server.sockets[0].getsockname()[1]
            self._started.set()
            async with server:
                await asyncio.Event().wait()  # until loop is stopped

        self._loop = asyncio.new_event_loop()
        try:
            self._loop.run_until_complete(main())
        except RuntimeError:
            pass

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


@pytest.fixture
def vec_server(monkeypatch):
    monkeypatch.setenv("RSDL_CLUSTER_TOKEN", "vec-test-secret")
    server = _TcpVecServer()
    yield server
    server.stop()


def test_vectored_over_authed_tcp(vec_server):
    conn = transport.Connection(("tcp", "127.0.0.1", vec_server.port))
    try:
        conn.send({"want": "vec"})
        obj, view = conn.recv_frame()
        assert obj == ("echo", {"want": "vec"})
        assert (
            bytes(view)
            == b"PAYLOAD:" + np.arange(64, dtype=np.int32).tobytes()
        )
    finally:
        conn.close()


def test_vectored_tcp_rejects_bad_token(vec_server, monkeypatch):
    """HMAC tamper: a peer holding the wrong secret is dropped before any
    frame — vectored or plain — is served."""
    monkeypatch.setenv("RSDL_CLUSTER_TOKEN", "WRONG-secret")
    conn = transport.Connection(("tcp", "127.0.0.1", vec_server.port))
    try:
        with pytest.raises((ConnectionError, OSError)):
            conn.send({"want": "vec"})
            conn.recv_frame()
    finally:
        conn.close()
