"""Compiled (non-interpreted) Pallas kernel validation on real TPU.

``tests/conftest.py`` pins the test process to the CPU platform before jax
initializes, so these checks run in a fresh subprocess that is allowed to
bring up the accelerator. They are gated behind ``RSDL_TPU_TESTS=1``: CI
has no TPU, and probing the plugin just to skip would cost minutes.

Round-1 VERDICT item 2: the kernel's interpreter-mode tests
(``tests/test_ops.py``) never proved Mosaic lowering works on hardware;
this module is that proof (first validated on v5e: exact forward match,
fp32-noise backward).
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("RSDL_TPU_TESTS") != "1",
    reason="set RSDL_TPU_TESTS=1 on a TPU host to run compiled-kernel tests",
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TPU_SCRIPT = r"""
import os
import sys
sys.path.insert(0, os.environ["RSDL_TEST_REPO"])
import numpy as np
import jax
import jax.numpy as jnp
from ray_shuffling_data_loader_tpu.ops import (
    dot_interaction,
    dot_interaction_reference,
)

assert jax.default_backend() == "tpu", jax.default_backend()

rng = np.random.default_rng(0)
# Ragged batch: exercises the padded tail tile in compiled mode too.
x = jnp.asarray(rng.standard_normal((1000, 27, 16)), dtype=jnp.float32)

ref = dot_interaction_reference(x)
# block_batch=256 is the VMEM-validated tile (512 exceeds the 16 MB scoped
# limit at this shape on v5e).
got = jax.jit(
    lambda x: dot_interaction(x, use_pallas=True, block_batch=256)
)(x)
err = float(jnp.max(jnp.abs(got - ref)))
assert err < 1e-4, f"forward mismatch: {err}"

g_ref = jax.grad(lambda x: (dot_interaction_reference(x) ** 2).sum())(x)
g_got = jax.grad(
    lambda x: (dot_interaction(x, use_pallas=True, block_batch=256) ** 2).sum()
)(x)
gerr = float(jnp.max(jnp.abs(g_got - g_ref)))
assert gerr < 1e-2, f"grad mismatch: {gerr}"

# Auto policy must pick the kernel here (single-device TPU).
auto = jax.jit(dot_interaction)(x)
aerr = float(jnp.max(jnp.abs(auto - ref)))
assert aerr < 1e-4, f"auto-path mismatch: {aerr}"

print("TPU_OPS_OK", err, gerr)
"""


def test_pallas_compiled_on_tpu():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the TPU plugin come up
    # The repo path rides an env var: the script body contains f-strings,
    # so str.format would mangle their braces.
    env["RSDL_TEST_REPO"] = _REPO
    proc = subprocess.run(
        [sys.executable, "-c", _TPU_SCRIPT],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
    )
    assert proc.returncode == 0 and "TPU_OPS_OK" in proc.stdout, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    )


_FLASH_SCRIPT = r"""
import os
import sys
sys.path.insert(0, os.environ["RSDL_TEST_REPO"])
import numpy as np
import jax
import jax.numpy as jnp
from ray_shuffling_data_loader_tpu.ops import attention_reference
from ray_shuffling_data_loader_tpu.ops.flash_attention import flash_attention

assert jax.default_backend() == "tpu", jax.default_backend()

rng = np.random.default_rng(1)
# (2, 1000, 4, 64): ragged T exercises the padded tail blocks. The small
# shapes are the model zoo's ACTUAL defaults, which route through this
# kernel by default on a single-device TPU: TabTransformer column tokens
# (~20 tokens, head_dim 8) and CausalLM (head_dim 16).
for shape in ((2, 1000, 4, 64), (32, 20, 4, 8), (8, 64, 4, 16)):
    for causal in (False, True):
        q, k, v = (
            jnp.asarray(rng.standard_normal(shape), jnp.float32)
            for _ in range(3)
        )
        got = jax.jit(
            lambda q, k, v: flash_attention(
                q, k, v, causal=causal, use_pallas=True, interpret=False
            )
        )(q, k, v)
        want = attention_reference(q, k, v, causal=causal)
        err = float(jnp.max(jnp.abs(got - want)))
        assert err < 1e-3, (shape, causal, err)
        print(f"FLASH_TPU {shape} causal={causal} max_err={err:.2e}", flush=True)

# Fused Pallas backward (dK/dV + dQ kernels) compiled on the real chip.
q, k, v = (
    jnp.asarray(rng.standard_normal((2, 256, 4, 32)), jnp.float32)
    for _ in range(3)
)
g_f = jax.jit(
    jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(
                q, k, v, causal=True, use_pallas=True, interpret=False
            )
            ** 2
        ),
        (0, 1, 2),
    )
)(q, k, v)
g_d = jax.grad(
    lambda q, k, v: jnp.sum(
        attention_reference(q, k, v, causal=True) ** 2
    ),
    (0, 1, 2),
)(q, k, v)
for gf, gd in zip(g_f, g_d):
    err = float(jnp.max(jnp.abs(gf - gd)))
    assert err < 1e-2, err
print("FLASH_TPU_BWD_OK", flush=True)
print("FLASH_TPU_OK", flush=True)
"""


def test_flash_attention_compiled_on_tpu():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["RSDL_TEST_REPO"] = _REPO
    proc = subprocess.run(
        [sys.executable, "-c", _FLASH_SCRIPT],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
    )
    assert proc.returncode == 0 and "FLASH_TPU_OK" in proc.stdout, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    )
