"""Multi-process JAX delivery: the pod path, on two CPU processes.

VERDICT r1 item 7: the ``jax.make_array_from_process_local_data`` branch of
``JaxShufflingDataset._put`` (the SURVEY §7 M3 pod-sharded global batch)
was never executed by a test. Here two real processes under
``jax.distributed`` (4 virtual CPU devices each -> one 8-device global
mesh) each consume their trainer rank's shard and assemble global arrays;
a jitted global-mean step then forces the cross-process collective.

Reference analog: the Horovod example's multi-worker consumption
(``/root/reference/examples/horovod/ray_torch_shuffle.py:319-344``).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Parameters reach the worker script via env (RSDL_T_*) — .format braces
# and python -c quoting stay out of the picture.
_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["RSDL_T_REPO"])

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["RSDL_T_COORD"],
    num_processes=2,
    process_id=int(os.environ["RSDL_T_RANK"]),
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import Mesh

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
from ray_shuffling_data_loader_tpu.data_generation import generate_data

rank = int(os.environ["RSDL_T_RANK"])
rdv = os.environ["RSDL_T_RDV"]
batch_size = 500

if rank == 0:
    ctx = runtime.init(num_workers=2)
    filenames, _ = generate_data(8000, 4, 1, 0.0, rdv + "/data")
    with open(rdv + "/runtime_dir.tmp", "w") as f:
        f.write(ctx.runtime_dir)
    os.rename(rdv + "/runtime_dir.tmp", rdv + "/runtime_dir")
else:
    deadline = time.time() + 120
    while not os.path.exists(rdv + "/runtime_dir"):
        assert time.time() < deadline, "rank0 session never appeared"
        time.sleep(0.2)
    with open(rdv + "/runtime_dir") as f:
        runtime.init(address=f.read().strip(), num_workers=2)
    filenames = sorted(
        os.path.join(rdv, "data", f) for f in os.listdir(rdv + "/data")
    )

mesh = Mesh(np.array(jax.devices()), ("data",))
ds = JaxShufflingDataset(
    filenames,
    num_epochs=1,
    num_trainers=2,
    batch_size=batch_size,
    rank=rank,
    feature_columns=["key", "embeddings_name0"],
    label_column="labels",
    num_reducers=2,
    seed=23,
    mesh=mesh,
    queue_name="q-mpjax",
)

ds.set_epoch(0)
batches = list(ds)
# Lockstep: every global-array computation is collective across the two
# processes, so both must run the same number of steps.
counts = multihost_utils.process_allgather(
    jnp.asarray([len(batches)], jnp.int32)
).reshape(-1)
steps = int(counts.min())
assert steps >= 1, f"rank {rank}: no common steps ({list(counts)})"

mean_fn = jax.jit(lambda feats, label: jnp.mean(label))
local_keys = []
global_batch_ok = True
for features, label in batches[:steps]:
    key_arr = features["key"]
    # Global batch spans both processes' shards.
    if key_arr.shape[0] != 2 * batch_size:
        global_batch_ok = False
    # The jitted reduction over a pod-sharded array is the collective.
    m = float(mean_fn(features, label))
    assert np.isfinite(m)
    for shard in key_arr.addressable_shards:
        local_keys.extend(np.asarray(shard.data).reshape(-1).tolist())

with open(f"{rdv}/keys_{rank}.tmp", "w") as f:
    json.dump(
        {"keys": local_keys, "batches": len(batches),
         "steps": steps, "global_batch_ok": global_batch_ok},
        f,
    )
os.rename(f"{rdv}/keys_{rank}.tmp", f"{rdv}/keys_{rank}")
# Drain remaining batches' acks happen inside the iterator already
# (list(ds) consumed everything); rank 0 owns the session shutdown.
multihost_utils.sync_global_devices("done")
runtime.shutdown()
print("MPJAX_RANK_DONE", rank, flush=True)
"""


_PACKED_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["RSDL_T_REPO"])

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["RSDL_T_COORD"],
    num_processes=2,
    process_id=int(os.environ["RSDL_T_RANK"]),
)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
from ray_shuffling_data_loader_tpu.data_generation import generate_data

rank = int(os.environ["RSDL_T_RANK"])
rdv = os.environ["RSDL_T_RDV"]

# Count global-array assemblies: the packed path must make exactly ONE
# per batch per process; the per-column path pays one per column + label.
counter = {"n": 0}
_orig_assemble = jax.make_array_from_process_local_data
def _counting(*a, **k):
    counter["n"] += 1
    return _orig_assemble(*a, **k)
jax.make_array_from_process_local_data = _counting

if rank == 0:
    ctx = runtime.init(num_workers=2)
    filenames, _ = generate_data(4000, 2, 1, 0.0, rdv + "/data")
    with open(rdv + "/runtime_dir.tmp", "w") as f:
        f.write(ctx.runtime_dir)
    os.rename(rdv + "/runtime_dir.tmp", rdv + "/runtime_dir")
else:
    deadline = time.time() + 120
    while not os.path.exists(rdv + "/runtime_dir"):
        assert time.time() < deadline
        time.sleep(0.2)
    with open(rdv + "/runtime_dir") as f:
        runtime.init(address=f.read().strip(), num_workers=2)
    filenames = sorted(
        os.path.join(rdv, "data", f) for f in os.listdir(rdv + "/data")
    )

mesh = Mesh(np.array(jax.devices()), ("data",))

def run(queue_name, force_percol):
    ds = JaxShufflingDataset(
        filenames,
        num_epochs=1,
        num_trainers=2,
        batch_size=500,
        rank=rank,
        feature_columns=["key", "embeddings_name0"],
        label_column="labels",
        num_reducers=2,
        seed=7,
        mesh=mesh,
        queue_name=queue_name,
    )
    if force_percol:
        ds._packed_ok = False
    ds.set_epoch(0)
    before = counter["n"]
    rows = []
    nb = 0
    for features, label in ds:
        nb += 1
        for arr in (features["key"], features["embeddings_name0"], label):
            for shard in arr.addressable_shards:
                rows.append(np.asarray(shard.data).reshape(-1).tolist())
    return nb, counter["n"] - before, rows

nb_packed, calls_packed, rows_packed = run("q-mp-packed", False)
nb_col, calls_col, rows_col = run("q-mp-percol", True)

assert nb_packed == nb_col, (nb_packed, nb_col)
# One assembly per batch (packed) vs one per column+label (per-column).
assert calls_packed == nb_packed, (calls_packed, nb_packed)
assert calls_col == 3 * nb_col, (calls_col, nb_col)
# Same seed => identical delivery; the two staging paths must be
# bit-identical shard by shard.
assert rows_packed == rows_col
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("done")
runtime.shutdown()
print("MPPACK_RANK_DONE", rank, flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_global_array_delivery(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    logs = []
    for rank in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            RSDL_T_REPO=_REPO,
            RSDL_T_COORD=coord,
            RSDL_T_RANK=str(rank),
            RSDL_T_RDV=str(tmp_path),
        )
        log = tmp_path / f"rank{rank}.log"
        logs.append(log)
        lf = open(log, "w")
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, "-u", "-c", _WORKER],
                    stdout=lf,
                    stderr=subprocess.STDOUT,
                    env=env,
                ),
                lf,
            )
        )
    try:
        for proc, _ in procs:
            proc.wait(timeout=420)
    finally:
        for proc, lf in procs:
            proc.kill()
            proc.wait()
            lf.close()
    outputs = [log.read_text() for log in logs]
    for rank, out in enumerate(outputs):
        assert f"MPJAX_RANK_DONE {rank}" in out, (
            f"rank{rank} log:\n{out[-4000:]}\n--- other rank:\n"
            f"{outputs[1 - rank][-4000:]}"
        )
    results = [
        json.load(open(tmp_path / f"keys_{rank}")) for rank in range(2)
    ]
    assert all(r["global_batch_ok"] for r in results)
    # Each process saw only its own addressable shard (its trainer rank's
    # rows): across processes the key sets must be disjoint and every key
    # delivered at most once (tails past the common step count excluded).
    k0, k1 = set(results[0]["keys"]), set(results[1]["keys"])
    assert len(k0) == len(results[0]["keys"])  # no dup within rank 0
    assert len(k1) == len(results[1]["keys"])
    assert not (k0 & k1), f"{len(k0 & k1)} keys delivered to both ranks"
    assert (k0 | k1) <= set(range(8000))
    # Substantially all rows arrive (only sub-batch_size tails may drop).
    assert len(k0 | k1) >= 8000 - 2 * 500


def test_two_process_packed_staging(tmp_path):
    """Packed single-transfer staging on a multi-controller pod: one
    global-array assembly per batch per process (vs one per column+label
    on the per-column path), bit-identical batches either way; the
    shard_map unpack launches at independent per-rank rates without a
    cross-host rendezvous."""
    coord = f"127.0.0.1:{_free_port()}"
    procs, logs = [], []
    for rank in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            RSDL_T_REPO=_REPO,
            RSDL_T_COORD=coord,
            RSDL_T_RANK=str(rank),
            RSDL_T_RDV=str(tmp_path),
        )
        log = tmp_path / f"rank{rank}.log"
        logs.append(log)
        lf = open(log, "w")
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, "-u", "-c", _PACKED_WORKER],
                    stdout=lf,
                    stderr=subprocess.STDOUT,
                    env=env,
                ),
                lf,
            )
        )
    try:
        for proc, _ in procs:
            proc.wait(timeout=420)
    finally:
        for proc, lf in procs:
            proc.kill()
            proc.wait()
            lf.close()
    outputs = [log.read_text() for log in logs]
    for rank, out in enumerate(outputs):
        assert f"MPPACK_RANK_DONE {rank}" in out, (
            f"rank{rank} log:\n{out[-4000:]}\n--- other rank:\n"
            f"{outputs[1 - rank][-4000:]}"
        )
