"""Ring attention vs dense reference on the 8-virtual-device mesh:
forward (causal and not), gradients, bf16, and sharding of the output.

No reference-repo analog (the reference has no attention, SURVEY §5);
this pins the sequence-parallel op the model layer uses for long
contexts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax_compat import (
    needs_kernel_partitioning_apis,
    needs_toplevel_shard_map,
)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_shuffling_data_loader_tpu.ops import (
    attention_reference,
    blockwise_attention,
    make_ring_attention,
    make_ulysses_attention,
)

B, T, H, D = 2, 64, 2, 8
SEQ_AXIS = "sp"


@pytest.fixture(scope="module")
def seq_mesh():
    return Mesh(np.array(jax.devices()), (SEQ_AXIS,))


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((B, T, H, D)).astype(np.float32), dtype=dtype
    )
    return mk(), mk(), mk()


@needs_toplevel_shard_map
@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense_reference(seq_mesh, causal):
    q, k, v = _qkv()
    ring = make_ring_attention(seq_mesh, SEQ_AXIS, causal=causal)
    got = ring(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    # Output stays sequence-sharded — no device gathered the full T.
    assert got.sharding.spec == (None, SEQ_AXIS, None, None)


@needs_toplevel_shard_map
def test_gradients_match_dense(seq_mesh):
    q, k, v = _qkv(seed=1)
    ring = make_ring_attention(seq_mesh, SEQ_AXIS, causal=True)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


@needs_toplevel_shard_map
def test_gradients_match_dense_noncausal(seq_mesh):
    """The custom ring VJP's non-causal branch (no mask recompute)."""
    q, k, v = _qkv(seed=7)
    ring = make_ring_attention(seq_mesh, SEQ_AXIS, causal=False)
    g_ring = jax.grad(
        lambda q, k, v: jnp.sum(ring(q, k, v) ** 2), (0, 1, 2)
    )(q, k, v)
    g_dense = jax.grad(
        lambda q, k, v: jnp.sum(attention_reference(q, k, v) ** 2),
        (0, 1, 2),
    )(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


@needs_kernel_partitioning_apis
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_hops_match(seq_mesh, causal):
    """Ring with per-hop compute forced through the flash kernel
    (interpret on CPU): the kernel's emitted (m, l) statistics merge
    across hops exactly; forward and the custom-VJP gradients match the
    dense reference."""
    q, k, v = _qkv(seed=10)
    ring = make_ring_attention(
        seq_mesh, SEQ_AXIS, causal=causal, use_flash=True
    )
    got = ring(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    g_r = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2), (0, 1, 2))(
        q, k, v
    )
    g_d = jax.grad(
        lambda q, k, v: jnp.sum(
            attention_reference(q, k, v, causal=causal) ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    for gr, gd in zip(g_r, g_d):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


@needs_kernel_partitioning_apis
def test_ulysses_flash_local_matches(seq_mesh):
    """Ulysses with the local body forced through the flash kernel
    (interpret mode on CPU) — the TPU lowering's exactness, fwd + grad."""
    rng = np.random.default_rng(8)
    shape = (1, 32, 8, 4)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        for _ in range(3)
    )
    fn = make_ulysses_attention(
        seq_mesh, SEQ_AXIS, causal=True, use_flash=True
    )
    got = fn(q, k, v)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    g_u = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2), (0, 1, 2))(
        q, k, v
    )
    g_d = jax.grad(
        lambda q, k, v: jnp.sum(
            attention_reference(q, k, v, causal=True) ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    for gu, gd in zip(g_u, g_d):
        np.testing.assert_allclose(
            np.asarray(gu), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


def test_blockwise_gradients_match_dense():
    """blockwise_attention's custom VJP (chunk recompute) vs dense."""
    rng = np.random.default_rng(9)
    shape = (1, 56, 2, 8)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        for _ in range(3)
    )
    from ray_shuffling_data_loader_tpu.ops import blockwise_attention

    g_b = jax.grad(
        lambda q, k, v: jnp.sum(
            blockwise_attention(q, k, v, causal=True, kv_chunk=24) ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    g_d = jax.grad(
        lambda q, k, v: jnp.sum(
            attention_reference(q, k, v, causal=True) ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    for gb, gd in zip(g_b, g_d):
        np.testing.assert_allclose(
            np.asarray(gb), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


@needs_toplevel_shard_map
def test_bfloat16_inputs(seq_mesh):
    q, k, v = _qkv(seed=2, dtype=jnp.bfloat16)
    ring = make_ring_attention(seq_mesh, SEQ_AXIS)
    got = ring(q, k, v)
    assert got.dtype == jnp.bfloat16
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32),
        np.asarray(want, dtype=np.float32),
        rtol=5e-2,
        atol=5e-2,
    )


@needs_toplevel_shard_map
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense_reference(seq_mesh, causal):
    """The all-to-all strategy: exact for any mask (full T per device),
    heads split across the axis (H=8 divides the 8-device mesh). The
    odd kv_chunk forces the blockwise path's ragged final chunk."""
    rng = np.random.default_rng(4)
    shape = (2, 64, 8, 4)  # heads divisible by the axis size
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        for _ in range(3)
    )
    fn = make_ulysses_attention(seq_mesh, SEQ_AXIS, causal=causal, kv_chunk=24)
    got = fn(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    assert got.sharding.spec == (None, SEQ_AXIS, None, None)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kv_chunk", [16, 24, 1024])
def test_blockwise_matches_dense(causal, kv_chunk):
    """Single-device KV-chunked attention (the Ulysses local compute):
    exact incl. ragged final chunk and chunk > T."""
    rng = np.random.default_rng(6)
    shape = (2, 56, 2, 8)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        for _ in range(3)
    )
    got = blockwise_attention(q, k, v, causal=causal, kv_chunk=kv_chunk)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@needs_toplevel_shard_map
def test_ulysses_gradients_match_dense(seq_mesh):
    rng = np.random.default_rng(5)
    shape = (1, 32, 8, 4)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        for _ in range(3)
    )
    fn = make_ulysses_attention(seq_mesh, SEQ_AXIS, causal=True)
    g_u = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2), (0, 1, 2))(
        q, k, v
    )
    g_d = jax.grad(
        lambda q, k, v: jnp.sum(
            attention_reference(q, k, v, causal=True) ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    for gu, gd in zip(g_u, g_d):
        np.testing.assert_allclose(
            np.asarray(gu), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


@needs_toplevel_shard_map
def test_respects_presharded_inputs(seq_mesh):
    """Feeding already-sequence-sharded arrays works and keeps shards."""
    q, k, v = _qkv(seed=3)
    sh = NamedSharding(seq_mesh, P(None, SEQ_AXIS, None, None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    ring = make_ring_attention(seq_mesh, SEQ_AXIS, causal=True)
    got = ring(q, k, v)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
