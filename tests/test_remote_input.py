"""URI dataset inputs (object-storage parity).

The reference reads only local paths (``pd.read_parquet`` of plain
filenames, reference ``shuffle.py:151``); TPU-VM pods read training data
from object storage. Every Parquet input site routes through
``utils.parquet_filesystem``: pyarrow-native filesystems for s3/gs/hdfs,
fsspec for any other scheme. These tests exercise the resolver with
schemes that need no cloud credentials — ``memory://`` (in-process) and
``file://`` (cross-process, so pool workers resolve it too).
"""

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu.data_generation import (
    DATA_SPEC,
    KEY_COLUMN,
    LABEL_COLUMN,
    generate_data,
)
from ray_shuffling_data_loader_tpu.utils import (
    is_remote_path,
    parquet_filesystem,
)


def test_local_path_passthrough():
    fs, rel = parquet_filesystem("/data/part-0.parquet")
    assert fs is None and rel == "/data/part-0.parquet"
    assert not is_remote_path("/data/part-0.parquet")
    assert is_remote_path("gs://bucket/part-0.parquet")


def test_memory_scheme_read_roundtrip():
    """An fsspec-only scheme (memory://) decodes through the same
    read_parquet_columns used by the mappers."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_shuffling_data_loader_tpu.shuffle import read_parquet_columns

    table = pa.table(
        {"key": np.arange(100, dtype=np.int64),
         "labels": np.ones(100, dtype=np.float64)}
    )
    fs, rel = parquet_filesystem("memory://ds/part-0.parquet")
    pq.write_table(table, rel, filesystem=fs)
    batch = read_parquet_columns("memory://ds/part-0.parquet")
    assert np.array_equal(batch.columns["key"], np.arange(100))
    assert set(batch.columns) == {"key", "labels"}


@pytest.fixture(scope="module")
def uri_files(local_runtime, tmp_path_factory):
    data_dir = tmp_path_factory.mktemp("uri-data")
    filenames, _ = generate_data(4000, 4, 1, 0.0, str(data_dir))
    return [f"file://{f}" for f in filenames]


def test_shuffle_dataset_from_file_uri(local_runtime, uri_files):
    """End-to-end map/reduce shuffle where every mapper (a separate pool
    worker process) decodes its input through the URI resolver."""
    from ray_shuffling_data_loader_tpu.dataset import ShufflingDataset

    ds = ShufflingDataset(
        uri_files,
        num_epochs=1,
        num_trainers=1,
        batch_size=1000,
        rank=0,
        num_reducers=2,
        queue_name="uri-q",
    )
    ds.set_epoch(0)
    keys = np.concatenate([np.asarray(b[KEY_COLUMN]) for b in ds])
    assert np.array_equal(np.sort(keys), np.arange(4000))


def test_resident_dataset_from_file_uri(local_runtime, uri_files):
    """Device-resident staging (footer reads + range decodes) over URIs."""
    from ray_shuffling_data_loader_tpu.resident import (
        DeviceResidentShufflingDataset,
        dataset_num_rows,
    )

    assert dataset_num_rows(uri_files) == 4000
    feature_columns = [KEY_COLUMN] + [
        c for c in list(DATA_SPEC)[:3] if c != LABEL_COLUMN
    ]
    ds = DeviceResidentShufflingDataset(
        uri_files,
        num_epochs=1,
        batch_size=1000,
        feature_columns=feature_columns,
        label_column=LABEL_COLUMN,
        seed=7,
    )
    ds.set_epoch(0)
    keys = np.concatenate(
        [np.asarray(f[KEY_COLUMN]) for f, _ in ds]
    )
    assert np.array_equal(np.sort(keys), np.arange(4000))
    ds.close()


def test_decode_threads_policy(monkeypatch):
    """Arrow per-read threads engage only when the host has idle cores
    beyond the concurrent decode tasks; env forces either way."""
    import ray_shuffling_data_loader_tpu.utils as utils

    monkeypatch.delenv("RSDL_DECODE_THREADS", raising=False)
    monkeypatch.setattr(utils.os, "cpu_count", lambda: 128)
    assert utils.decode_use_threads(16) is True  # 128 >= 2*16
    assert utils.decode_use_threads(64) is True
    assert utils.decode_use_threads(65) is False
    monkeypatch.setattr(utils.os, "cpu_count", lambda: 1)
    assert utils.decode_use_threads(1) is False
    monkeypatch.setenv("RSDL_DECODE_THREADS", "on")
    assert utils.decode_use_threads(10**6) is True
    monkeypatch.setenv("RSDL_DECODE_THREADS", "off")
    assert utils.decode_use_threads(1) is False


def test_threaded_decode_same_columns(local_runtime, uri_files):
    """Threaded and single-threaded decode produce identical columns."""
    from ray_shuffling_data_loader_tpu.shuffle import read_parquet_columns

    a = read_parquet_columns(uri_files[0], use_threads=False)
    b = read_parquet_columns(uri_files[0], use_threads=True)
    assert set(a.columns) == set(b.columns)
    for k in a.columns:
        assert np.array_equal(a.columns[k], b.columns[k])


def test_arrow_decode_threads_caps_pool(monkeypatch):
    """When threads engage, Arrow's process-global pool is capped to the
    task's fair share of the host (uncapped, N concurrent readers would
    run N x cores threads — the oversubscription the policy exists to
    avoid)."""
    import pyarrow as pa

    import ray_shuffling_data_loader_tpu.utils as utils

    monkeypatch.delenv("RSDL_DECODE_THREADS", raising=False)
    before = pa.cpu_count()
    try:
        monkeypatch.setattr(utils.os, "cpu_count", lambda: 128)
        assert utils.arrow_decode_threads(16) is True
        assert pa.cpu_count() == 8  # 128 cores / 16 concurrent tasks
        # Saturated host: stays single-threaded, pool untouched.
        pa.set_cpu_count(before)
        monkeypatch.setattr(utils.os, "cpu_count", lambda: 16)
        assert utils.arrow_decode_threads(16) is False
        assert pa.cpu_count() == before
        # stage_tasks beyond cores clamps to cores (concurrency on this
        # host cannot exceed its own core count).
        monkeypatch.setattr(utils.os, "cpu_count", lambda: 256)
        assert utils.arrow_decode_threads(100000) is False
    finally:
        pa.set_cpu_count(before)


def test_generate_data_to_uri(local_runtime, tmp_path):
    """Synthetic data generation writes straight to a URI destination
    (pool workers resolve it too); reading back is exactly-once."""
    from ray_shuffling_data_loader_tpu.data_generation import generate_data
    from ray_shuffling_data_loader_tpu.shuffle import read_parquet_columns

    out = tmp_path / "gen-uri"
    out.mkdir()
    filenames, nbytes = generate_data(2000, 2, 1, 0.0, f"file://{out}")
    assert nbytes > 0 and len(filenames) >= 2
    assert all(f.startswith("file://") for f in filenames)
    keys = np.concatenate(
        [np.asarray(read_parquet_columns(f).columns[KEY_COLUMN])
         for f in filenames]
    )
    assert np.array_equal(np.sort(keys), np.arange(2000))
