"""C++ data-plane kernel tests: every native op must agree exactly with
its numpy fallback (the fallback is the executable spec), and the shuffle
pipeline must produce identical results with the native library disabled.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import native


rng = np.random.default_rng(1234)


def test_native_builds():
    # The environment ships g++, so the build must succeed here; skipping
    # would hide a broken kernel file.
    assert native.native_available()


def test_take_matches_numpy():
    arr = rng.integers(0, 1 << 40, size=10_001)
    idx = rng.permutation(len(arr))
    np.testing.assert_array_equal(native.take(arr, idx), arr[idx])
    # repeats and subsets
    idx2 = rng.integers(0, len(arr), size=137)
    np.testing.assert_array_equal(native.take(arr, idx2), arr[idx2])


def test_take_2d_and_small_dtypes():
    m = rng.random((1000, 3)).astype(np.float32)
    idx = rng.permutation(1000)
    np.testing.assert_array_equal(native.take(m, idx), m[idx])
    b = rng.integers(0, 255, size=5000).astype(np.uint8)
    np.testing.assert_array_equal(native.take(b, idx), b[idx])


def test_take_multi_fused_concat_gather():
    parts = [
        rng.integers(0, 100, size=n) for n in (1000, 1, 5000, 0, 333)
    ]
    cat = np.concatenate(parts)
    idx = rng.permutation(len(cat))
    np.testing.assert_array_equal(native.take_multi(parts, idx), cat[idx])


def test_take_multi_sparse_gather():
    """Sparse multi-part gathers (idx << total rows — the index-schedule
    reduce) must match the dense semantics on every code path, including
    the no-concat numpy fallback."""
    parts = [
        rng.integers(0, 1 << 20, size=n) for n in (4000, 0, 9000, 17, 2500)
    ]
    cat = np.concatenate(parts)
    idx = rng.choice(len(cat), size=len(cat) // 8, replace=False)
    np.testing.assert_array_equal(native.take_multi(parts, idx), cat[idx])
    # out= destination, 2-D rows, and the pure-numpy sparse path.
    parts2d = [rng.random((n, 3)) for n in (700, 1200, 5)]
    cat2d = np.concatenate(parts2d)
    idx2 = rng.choice(len(cat2d), size=64, replace=False)
    out = np.empty((64, 3))
    got = native.take_multi(parts2d, idx2, out=out)
    np.testing.assert_array_equal(got, cat2d[idx2])
    from ray_shuffling_data_loader_tpu.native import _take_multi_sparse

    np.testing.assert_array_equal(
        _take_multi_sparse(parts2d, idx2.astype(np.int64), None), cat2d[idx2]
    )
    # Mixed-dtype parts must keep numpy's concat promotion semantics (the
    # sparse scatter assumes parts[0]'s dtype and would silently truncate).
    mixed = [
        np.arange(100, dtype=np.int32),
        np.arange(100, dtype=np.int64) + (1 << 40),
    ]
    mcat = np.concatenate(mixed)
    midx = np.array([5, 150, 199])
    got = native.take_multi(mixed, midx)
    np.testing.assert_array_equal(got, mcat[midx])
    assert got.dtype == mcat.dtype


def test_narrow_casts():
    a = rng.integers(0, 2**31 - 1, size=9999)
    np.testing.assert_array_equal(
        native.narrow(a, np.int32), a.astype(np.int32)
    )
    f = rng.random(9999)
    np.testing.assert_array_equal(
        native.narrow(f, np.float32), f.astype(np.float32)
    )
    # identity: no copy
    i32 = a.astype(np.int32)
    assert native.narrow(i32, np.int32) is i32


def test_group_rows_stable():
    arr = rng.integers(0, 1 << 40, size=20_000)
    assign = rng.integers(0, 7, size=len(arr))
    grouped, offsets = native.group_rows(arr, assign, 7)
    order = np.argsort(assign, kind="stable")
    np.testing.assert_array_equal(grouped, arr[order])
    counts = np.bincount(assign, minlength=7)
    np.testing.assert_array_equal(np.diff(offsets), counts)
    # empty groups allowed
    assign0 = np.zeros(len(arr), dtype=np.int64)
    g0, off0 = native.group_rows(arr, assign0, 3)
    np.testing.assert_array_equal(g0, arr)
    assert off0[1] == off0[2] == off0[3] == len(arr)


def test_group_rows_parallel_bit_identity():
    """The two-pass parallel stable scatter must be BIT-identical to the
    serial kernel and to the numpy argsort spec — across itemsizes
    (1/2/4/8 and an odd 3-byte row), thread counts, empty groups, and
    non-power-of-two row counts (thread ranges then split unevenly)."""
    for n in (20_001, 1_048_577):  # non-power-of-two on both sides of MT
        cols = {
            "w1": rng.integers(0, 255, size=n).astype(np.uint8),
            "w2": rng.integers(0, 1 << 14, size=n).astype(np.uint16),
            "w4": rng.integers(0, 1 << 30, size=n).astype(np.int32),
            "w8": rng.integers(0, 1 << 40, size=n),
            "odd": rng.integers(0, 255, size=(n, 3)).astype(np.uint8),
        }
        # group 3 left empty on purpose
        assign = rng.choice([0, 1, 2, 4, 5], size=n)
        order = np.argsort(assign, kind="stable")
        serial = {k: v[order] for k, v in cols.items()}
        for t in (1, 2, 8):
            got, offsets = native.group_rows_multi(
                cols, assign, 6, n_threads=t
            )
            for k in cols:
                assert got[k].tobytes() == serial[k].tobytes(), (n, t, k)
            assert offsets[4] == offsets[3]  # empty group
            np.testing.assert_array_equal(
                np.diff(offsets), np.bincount(assign, minlength=6)
            )


def test_group_rows_parallel_out_views():
    """Parallel path writing into pre-allocated out= destinations (the
    map stage's store-segment views)."""
    n = 1_200_000
    cols = {"a": rng.integers(0, 1 << 30, size=n).astype(np.int32)}
    assign = rng.integers(0, 8, size=n)
    out = {"a": np.empty_like(cols["a"])}
    got, _ = native.group_rows_multi(cols, assign, 8, out=out, n_threads=8)
    assert got["a"] is out["a"]
    order = np.argsort(assign, kind="stable")
    np.testing.assert_array_equal(out["a"], cols["a"][order])


def test_scatter_matches_numpy():
    """out[idx] = src across dtypes/threads; permutation-derived indices
    (the overlapped reduce's per-window placement op)."""
    n = 10_000
    perm = rng.permutation(n)
    for arr in (
        rng.integers(0, 1 << 30, size=n).astype(np.int32),
        rng.random((n, 2)).astype(np.float32),
        rng.integers(0, 255, size=(n, 3)).astype(np.uint8),
        rng.integers(0, 1 << 40, size=n),
    ):
        for t in (1, 2, 8):
            out = np.zeros_like(arr)
            ref = np.zeros_like(arr)
            ref[perm] = arr
            got = native.scatter(arr, perm, out, n_threads=t)
            assert got is out
            np.testing.assert_array_equal(out, ref)
    # windowed slice of an inverted permutation (the real call shape)
    inv = np.empty(n, dtype=np.int64)
    inv[perm] = np.arange(n)
    src = rng.integers(0, 1 << 30, size=n // 4).astype(np.int32)
    out = np.zeros(n, dtype=np.int32)
    ref = np.zeros(n, dtype=np.int32)
    ref[inv[: n // 4]] = src
    native.scatter(src, inv[: n // 4], out)
    np.testing.assert_array_equal(out, ref)


def test_scatter_bounds_and_fallbacks():
    arr = rng.integers(0, 100, size=10)
    out = np.zeros(10, dtype=arr.dtype)
    with pytest.raises(IndexError):
        native.scatter(arr, np.arange(5, 15), out)
    with pytest.raises(ValueError):
        native.scatter(arr, np.arange(3), out)
    # negative indices route to the numpy fallback's semantics
    out[:] = 0
    native.scatter(arr[:2], np.array([-1, -2]), out)
    assert out[-1] == arr[0] and out[-2] == arr[1]


def test_native_threads_env_knob(monkeypatch):
    """RSDL_NATIVE_THREADS overrides the core-count heuristic, read once
    and clamped >= 1."""
    default = native.num_threads()
    assert default >= 1
    monkeypatch.setenv(native.ENV_THREADS, "5")
    native.refresh_threads_from_env()
    assert native.num_threads() == 5
    monkeypatch.setenv(native.ENV_THREADS, "0")
    native.refresh_threads_from_env()
    assert native.num_threads() == 1  # clamped
    monkeypatch.setenv(native.ENV_THREADS, "junk")
    native.refresh_threads_from_env()
    assert native.num_threads() == default  # unparsable -> heuristic
    monkeypatch.delenv(native.ENV_THREADS)
    native.refresh_threads_from_env()
    assert native.num_threads() == default


def test_take_bounds_semantics():
    arr = rng.integers(0, 100, size=100)
    # negative indices: numpy semantics via fallback
    np.testing.assert_array_equal(
        native.take(arr, np.array([-1, -100, 5])), arr[[-1, -100, 5]]
    )
    with pytest.raises(IndexError):
        native.take(arr, np.array([0, 100]))
    with pytest.raises(IndexError):
        native.take(arr, np.array([-101]))


def test_group_rows_multi_shared_assignment():
    cols = {
        "a": rng.integers(0, 1 << 30, size=5000),
        "b": rng.random(5000).astype(np.float32),
    }
    assign = rng.integers(0, 5, size=5000)
    grouped, offsets = native.group_rows_multi(cols, assign, 5)
    order = np.argsort(assign, kind="stable")
    for k in cols:
        np.testing.assert_array_equal(grouped[k], cols[k][order])
    np.testing.assert_array_equal(
        np.diff(offsets), np.bincount(assign, minlength=5)
    )


def test_shuffle_identical_with_native_disabled(tmp_path):
    """The shuffle permutation must not depend on whether the C++ kernels
    are loaded: run the map+reduce stages in-process under both settings
    and compare bytes."""
    script = r"""
import numpy as np
from ray_shuffling_data_loader_tpu import native
from ray_shuffling_data_loader_tpu.runtime.store import ColumnBatch
rng = np.random.default_rng(7)
cols = {
    "a": rng.integers(0, 1 << 30, size=10000),
    "b": rng.random(10000),
}
assign = rng.integers(0, 4, size=10000)
out, offsets = native.group_rows_multi(cols, assign, 4)
perm = rng.permutation(10000)
parts = [ColumnBatch({k: v[offsets[i]:offsets[i+1]] for k, v in out.items()}) for i in range(4)]
final = ColumnBatch.concat_take(parts, perm)
print(repr(hash((final["a"].tobytes(), final["b"].tobytes()))))
"""
    outputs = []
    for disable in ("", "1"):
        env = dict(os.environ, RSDL_DISABLE_NATIVE=disable)
        env.pop("PYTHONHASHSEED", None)
        env["PYTHONHASHSEED"] = "0"
        res = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            env=env,
            check=True,
        )
        outputs.append(res.stdout.strip())
    assert outputs[0] == outputs[1], outputs
