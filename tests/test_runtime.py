"""Tests for the runtime substrate: store, actors, worker pool.

The reference has no equivalent (it leans on Ray core); these cover the
replacement layer (SURVEY.md §2b)."""

import threading
import time

import numpy as np
import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.runtime import ColumnBatch
from ray_shuffling_data_loader_tpu.runtime.tasks import TaskError, wait


# -- object store -----------------------------------------------------------


def test_create_columns_direct_write(local_runtime):
    """The zero-copy write path: fill mmapped views, seal, read back."""
    store = runtime.get_context().store
    pending = store.create_columns(
        {"a": ((10,), np.int64), "b": ((10, 3), np.float32)}
    )
    pending.columns["a"][:] = np.arange(10)
    pending.columns["b"][:] = np.ones((10, 3), np.float32)
    ref = pending.seal()
    got = store.get_columns(ref)
    np.testing.assert_array_equal(got["a"], np.arange(10))
    np.testing.assert_array_equal(got["b"], np.ones((10, 3), np.float32))
    del got
    store.free(ref)
    assert store.store_stats().num_objects == 0


def test_publish_slices_hardlink_refcount(local_runtime):
    """Window refs share one physical segment; pages survive until the
    LAST window is freed (filesystem-refcount semantics)."""
    store = runtime.get_context().store
    pending = store.create_columns({"x": ((9,), np.int64)})
    pending.columns["x"][:] = np.arange(9)
    refs = pending.publish_slices([(0, 3), (3, 6), (6, 9)])
    assert [r.rows for r in refs] == [(0, 3), (3, 6), (6, 9)]
    # Bytes counted once despite three links.
    stats = store.store_stats()
    assert stats.num_objects == 3
    for i, ref in enumerate(refs):
        np.testing.assert_array_equal(
            store.get_columns(ref)["x"], np.arange(3 * i, 3 * i + 3)
        )
    # Free two; the third still reads.
    store.free(refs[:2])
    np.testing.assert_array_equal(
        store.get_columns(refs[2])["x"], np.arange(6, 9)
    )
    store.free(refs[2])
    assert store.store_stats().num_objects == 0


def test_pending_abort_reclaims(local_runtime, tmp_path):
    store = runtime.get_context().store
    pending = store.create_columns({"x": ((4,), np.int64)})
    pending.abort()
    assert store.store_stats().num_objects == 0
    pending.abort()  # idempotent


def test_serialize_columns_roundtrip(local_runtime, tmp_path):
    """Wire format == disk format (shared layout planner): bytes written
    to a file map back identically — the DCN windowed-fetch path."""
    from ray_shuffling_data_loader_tpu.runtime.store import (
        map_segment_file,
        serialize_columns,
    )

    cols = {
        "a": np.arange(7, dtype=np.int32),
        "b": np.linspace(0, 1, 7).astype(np.float64),
    }
    blob = serialize_columns(cols)
    path = tmp_path / "seg"
    path.write_bytes(blob)
    got = map_segment_file(str(path))
    np.testing.assert_array_equal(got["a"], cols["a"])
    np.testing.assert_array_equal(got["b"], cols["b"])


def test_out_mismatch_raises(local_runtime):
    """Strict out= contract: a destination that can't hold the result is a
    loud error, never a silent fallback (would publish zeros)."""
    from ray_shuffling_data_loader_tpu import native

    arr = np.arange(10, dtype=np.int64)
    bad_out = np.empty(5, dtype=np.int64)
    with pytest.raises(ValueError, match="out="):
        native.take(arr, np.arange(10), out=bad_out)
    with pytest.raises(ValueError, match="out="):
        native.take_multi(
            [arr, arr], np.arange(20), out=np.empty(20, np.int32)
        )


def test_store_roundtrip(local_runtime):
    store = local_runtime.store
    cols = {
        "a": np.arange(100, dtype=np.int64),
        "b": np.random.default_rng(0).random(100),
    }
    ref = store.put_columns(cols)
    out = store.get_columns(ref)
    assert list(out) == ["a", "b"]
    np.testing.assert_array_equal(out["a"], cols["a"])
    np.testing.assert_array_equal(out["b"], cols["b"])
    assert out.num_rows == 100
    stats = store.store_stats()
    assert stats.num_objects >= 1
    assert stats.total_bytes > 0
    store.free(ref)
    assert not store.exists(ref)


def test_store_views_survive_free(local_runtime):
    # The iterator frees segments while still holding views; pages must
    # stay valid until the last view drops (POSIX unlink semantics).
    store = local_runtime.store
    ref = store.put_columns({"x": np.arange(1000)})
    batch = store.get_columns(ref)
    store.free(ref)
    np.testing.assert_array_equal(batch["x"], np.arange(1000))


def test_column_batch_ops():
    cb = ColumnBatch({"a": np.arange(10), "b": np.arange(10) * 2.0})
    taken = cb.take(np.array([3, 1, 4]))
    np.testing.assert_array_equal(taken["a"], [3, 1, 4])
    sliced = cb.slice(2, 5)
    assert sliced.num_rows == 3
    cat = ColumnBatch.concat([cb.slice(0, 4), cb.slice(4, 10)])
    np.testing.assert_array_equal(cat["a"], np.arange(10))
    with pytest.raises(ValueError):
        ColumnBatch({"a": np.arange(3), "b": np.arange(4)})


# -- worker pool ------------------------------------------------------------


def _square(x):
    return x * x


def _boom():
    raise RuntimeError("boom")


def _slow(x, delay):
    time.sleep(delay)
    return x


def test_pool_submit(local_runtime):
    futs = [runtime.submit(_square, i) for i in range(8)]
    assert [f.result(timeout=30) for f in futs] == [i * i for i in range(8)]


def test_pool_error(local_runtime):
    fut = runtime.submit(_boom)
    with pytest.raises(TaskError, match="boom"):
        fut.result(timeout=30)


def test_pool_wait(local_runtime):
    futs = [runtime.submit(_slow, i, 0.05 * i) for i in range(3)]
    done, pending = wait(futs, num_returns=1, timeout=30)
    assert len(done) >= 1


def test_wait_event_driven(local_runtime):
    """wait() blocks on completion notification, not a spin loop: an
    unfulfilled future times out without burning CPU, and a fulfillment
    mid-wait wakes the waiter promptly."""
    import os as _os
    import time as _time

    from ray_shuffling_data_loader_tpu.runtime.tasks import TaskFuture

    fut = TaskFuture(0)
    cpu0 = _os.times()
    t0 = _time.monotonic()
    done, pending = wait([fut], num_returns=1, timeout=0.5)
    waited = _time.monotonic() - t0
    cpu1 = _os.times()
    assert done == [] and pending == [fut]
    assert waited >= 0.45
    # A 1 ms poll burned ~full core here before; event-driven is near zero.
    cpu_used = (cpu1.user - cpu0.user) + (cpu1.system - cpu0.system)
    assert cpu_used < 0.2, f"wait() burned {cpu_used:.3f}s CPU in {waited:.2f}s"

    fut2 = TaskFuture(1)
    threading.Timer(0.1, lambda: fut2._fulfill("ok", None)).start()
    t0 = _time.monotonic()
    done, pending = wait([fut2], num_returns=1, timeout=30)
    assert done == [fut2] and _time.monotonic() - t0 < 5
    # No waiters leak on the fulfilled future.
    assert fut2._waiters == []


def test_wait_on_already_done_cluster_future():
    """Regression: waiting on an already-completed ClusterTaskFuture must
    not deadlock (add_done_callback fires synchronously when done; the
    notify path re-takes the waiter lock)."""
    import concurrent.futures

    from ray_shuffling_data_loader_tpu.runtime.cluster import ClusterTaskFuture

    inner = concurrent.futures.Future()
    inner.set_result(42)
    fut = ClusterTaskFuture(inner)
    done, pending = wait([fut], num_returns=1, timeout=5)
    assert done == [fut] and pending == []
    assert fut.result() == 42


def test_prefetch_overlaps_foreign_fetches(tmp_path):
    """``prefetch`` pulls foreign refs' windows concurrently (the
    ``ray.wait(fetch_local=True)`` analog) and later ``get_columns`` hit
    the local cache — no extra remote fetch per ref."""
    from ray_shuffling_data_loader_tpu.runtime.store import (
        ObjectRef,
        ObjectStore,
        serialize_columns,
    )

    store = ObjectStore("pfsess", shm_dir=str(tmp_path))
    store.owner_address = ("tcp", "local", 1)
    payload = serialize_columns({"x": np.arange(32, dtype=np.int64)})
    state = {"active": 0, "max_active": 0, "fetches": 0}
    lock = threading.Lock()

    def fake_fetch(ref):
        with lock:
            state["active"] += 1
            state["max_active"] = max(state["max_active"], state["active"])
        time.sleep(0.15)
        with lock:
            state["active"] -= 1
            state["fetches"] += 1
        return payload

    store.remote_fetch = fake_fetch
    refs = [
        ObjectRef(
            object_id=f"othersess-{i:02d}",
            nbytes=len(payload),
            session="othersess",
            owner=("tcp", "remote", 2),
        )
        for i in range(4)
    ]
    t0 = time.monotonic()
    futs = store.prefetch(refs)
    assert len(futs) == 4
    for f in futs:
        f.result(timeout=30)
    elapsed = time.monotonic() - t0
    # 4 fetches of 0.15 s each: serial would be >= 0.6 s.
    assert state["max_active"] >= 2, "fetches never overlapped"
    assert elapsed < 0.45, f"prefetch looks serial: {elapsed:.2f}s"
    assert state["fetches"] == 4
    # Consumption now hits the cache: no new remote fetches.
    for ref in refs:
        cb = store.get_columns(ref)
        np.testing.assert_array_equal(cb["x"], np.arange(32))
    assert state["fetches"] == 4
    # Already-cached refs are skipped entirely.
    assert store.prefetch(refs) == []
    store.free(refs)


def test_prefetch_pool_grows_with_max_parallel(tmp_path):
    """The prefetch pool's width follows the LARGEST ``max_parallel``
    seen: a first narrow call must not pin later, wider callers to
    serialized fetches (ISSUE 6 satellite — the old pool bound its
    width on the first call forever)."""
    from ray_shuffling_data_loader_tpu.runtime.store import (
        ObjectRef,
        ObjectStore,
        serialize_columns,
    )

    store = ObjectStore("pfgrow", shm_dir=str(tmp_path))
    store.owner_address = ("tcp", "local", 1)
    payload = serialize_columns({"x": np.arange(8, dtype=np.int64)})
    state = {"active": 0, "max_active": 0}
    lock = threading.Lock()

    def fake_fetch(ref):
        with lock:
            state["active"] += 1
            state["max_active"] = max(state["max_active"], state["active"])
        time.sleep(0.1)
        with lock:
            state["active"] -= 1
        return payload

    store.remote_fetch = fake_fetch

    def make_refs(tag, n):
        return [
            ObjectRef(
                object_id=f"other-{tag}-{i:02d}",
                nbytes=len(payload),
                session="other",
                owner=("tcp", "remote", 2),
            )
            for i in range(n)
        ]

    # First caller pins a width of 1...
    futs = store.prefetch(make_refs("narrow", 2), max_parallel=1)
    for f in futs:
        f.result(timeout=30)
    assert store._prefetch_pool.width == 1
    assert state["max_active"] == 1
    # ...a later wider call must actually fetch in parallel.
    state["max_active"] = 0
    futs = store.prefetch(make_refs("wide", 4), max_parallel=4)
    for f in futs:
        f.result(timeout=30)
    assert store._prefetch_pool.width == 4
    assert state["max_active"] >= 2, "pool never grew"


# -- actors -----------------------------------------------------------------


class Counter:
    def __init__(self, start=0):
        self.value = start

    def incr(self, by=1):
        self.value += by
        return self.value

    def get(self):
        return self.value

    async def slow_get(self, delay):
        import asyncio

        await asyncio.sleep(delay)
        return self.value

    def fail(self):
        raise ValueError("actor failure")


def test_actor_call(local_runtime):
    h = runtime.spawn_actor(Counter, 10)
    assert h.call("incr") == 11
    assert h.call("incr", by=5) == 16
    assert h.call("get") == 16
    with pytest.raises(ValueError, match="actor failure"):
        h.call("fail")
    h.terminate()


def test_actor_named_discovery(local_runtime):
    h = runtime.spawn_actor(Counter, 7, name="counter-disco")
    h2 = runtime.connect_actor("counter-disco")
    assert h2.call("get") == 7
    h2.call("incr")
    assert h.call("get") == 8
    h.terminate()


def test_actor_concurrent_async_methods(local_runtime):
    # A blocked async method must not stall other calls (the queue relies
    # on this: a blocked `get` with a concurrent `put`).
    h = runtime.spawn_actor(Counter, 1)
    results = {}

    def slow():
        results["slow"] = h.call("slow_get", 0.8)

    t = threading.Thread(target=slow)
    t.start()
    time.sleep(0.1)
    start = time.monotonic()
    assert h.call("get") == 1  # must return before slow_get completes
    assert time.monotonic() - start < 0.6
    t.join()
    assert results["slow"] == 1
    h.terminate()


def test_actor_terminate_then_call_raises(local_runtime):
    h = runtime.spawn_actor(Counter, 0)
    h.terminate()
    with pytest.raises(runtime.ActorDiedError):
        h.call("get")


def test_connect_unknown_actor_fails(local_runtime):
    with pytest.raises(ValueError, match="Unable to connect"):
        runtime.connect_actor("no-such-actor", num_retries=1)
