"""tools/audit_report.py smoke tests against synthetic CSV/JSON artifacts
(the CI-lane guard for the report CLI: parse, join, render, exit code)."""

import csv
import importlib.util
import json
import os

import pytest


@pytest.fixture(scope="module")
def audit_report():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools",
        "audit_report.py",
    )
    spec = importlib.util.spec_from_file_location("audit_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_artifacts(tmp_path, ok=True):
    epochs = [
        {
            "epoch": 0,
            "ok": True,
            "mismatch": [],
            "rows_mapped": 2000,
            "rows_reduced": 2000,
            "rows_delivered": 2000,
            "rows_consumed": 2000,
            "map_digest": "aa:bb",
            "reduce_digest": "aa:bb",
            "delivered_digest": "aa:bb",
            "delivered_seq": "cafe",
            "adjacent_pair_retention": None,
            "mean_normalized_displacement": None,
            "source_entropy_mean": 0.99,
        },
        {
            "epoch": 1,
            "ok": ok,
            "mismatch": [] if ok else ["delivered"],
            "rows_mapped": 2000,
            "rows_reduced": 2000,
            "rows_delivered": 2000 if ok else 1999,
            "rows_consumed": 2000 if ok else 1999,
            "map_digest": "aa:bb",
            "reduce_digest": "aa:bb",
            "delivered_digest": "aa:bb" if ok else "dd:ee",
            "delivered_seq": "beef",
            "adjacent_pair_retention": 0.001,
            "mean_normalized_displacement": 0.34,
            "source_entropy_mean": 0.98,
        },
    ]
    bench = {
        "metric": "m",
        "value": 1.5,
        "unit": "GB/s/chip",
        "vs_baseline": 0.9,
        "stall_pct": 3.2,
        "backend": "cpu",
        "loader": "mapreduce",
        "audit": {
            "ok": ok,
            "mismatch_epochs": [] if ok else [1],
            "epochs": epochs,
        },
    }
    bench_path = str(tmp_path / "bench.json")
    with open(bench_path, "w") as f:
        # Log noise around the JSON line exercises the tolerant parser.
        f.write("[bench] some log line\n")
        f.write(json.dumps(bench) + "\n")
    metrics_payload = {
        "samples": [],
        "final": {
            "audit.rows_mapped": 4000.0,
            "audit.rows_delivered": 4000.0 if ok else 3999.0,
            "audit.digest_mismatch": 0.0 if ok else 1.0,
            "audit.epoch_ok{epoch=0}": 1.0,
            "audit.epoch_ok{epoch=1}": 1.0 if ok else 0.0,
            "audit.adjacent_pair_retention{epoch=1}": 0.001,
        },
    }
    metrics_path = str(tmp_path / "run.metrics.json")
    with open(metrics_path, "w") as f:
        json.dump(metrics_payload, f)
    trial_path = str(tmp_path / "trial_stats.csv")
    with open(trial_path, "w", newline="") as f:
        w = csv.DictWriter(
            f,
            fieldnames=[
                "trial", "duration", "num_rows", "num_epochs",
                "row_throughput", "audit_epochs_ok",
                "audit_mismatch_epochs",
            ],
        )
        w.writeheader()
        w.writerow(
            {
                "trial": 0,
                "duration": 12.5,
                "num_rows": 2000,
                "num_epochs": 2,
                "row_throughput": 320.0,
                "audit_epochs_ok": 2 if ok else 1,
                "audit_mismatch_epochs": "" if ok else "1",
            }
        )
    epoch_path = str(tmp_path / "epoch_stats.csv")
    with open(epoch_path, "w", newline="") as f:
        w = csv.DictWriter(
            f,
            fieldnames=[
                "trial", "epoch", "duration", "map_stage_duration",
                "reduce_stage_duration", "throttle_duration",
            ],
        )
        w.writeheader()
        for e in (0, 1):
            w.writerow(
                {
                    "trial": 0,
                    "epoch": e,
                    "duration": 5.0 + e,
                    "map_stage_duration": 2.0,
                    "reduce_stage_duration": 1.5,
                    "throttle_duration": 0.1,
                }
            )
    return bench_path, metrics_path, trial_path, epoch_path


def test_full_join_renders_table(audit_report, tmp_path, capsys):
    bench, metrics, trial, epoch = _write_artifacts(tmp_path, ok=True)
    rc = audit_report.main(
        [
            "--bench", bench, "--metrics", metrics,
            "--trial-csv", trial, "--epoch-csv", epoch,
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    # Header joins bench + trial CSV + metrics counters.
    assert "value: 1.5" in out
    assert "row_throughput: 320.0" in out
    assert "audit.rows_mapped: 4000" in out
    # Per-epoch rows join verdicts with epoch-CSV timings.
    assert "rows_delivered" in out and "epoch_s" in out
    lines = [ln for ln in out.splitlines() if ln.strip().startswith(("0 ", "0  "))]
    assert any("2000" in ln and "5" in ln for ln in lines), out


def test_mismatch_sets_exit_code(audit_report, tmp_path, capsys):
    bench, metrics, trial, epoch = _write_artifacts(tmp_path, ok=False)
    rc = audit_report.main(["--bench", bench])
    out = capsys.readouterr().out
    assert rc == 1
    assert "MISMATCH" in out
    assert "mismatch_epochs: [1]" in out


def test_metrics_only_fallback(audit_report, tmp_path, capsys):
    _, metrics, _, _ = _write_artifacts(tmp_path, ok=True)
    rc = audit_report.main(["--metrics", metrics, "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    # Verdict rows reconstructed from the audit.* gauge vocabulary.
    assert [e["epoch"] for e in report["epochs"]] == [0, 1]
    assert report["epochs"][1]["adjacent_pair_retention"] == 0.001
    assert report["header"]["audit_ok"] is True


def test_zero_coverage_is_not_a_pass(audit_report, tmp_path, capsys):
    """Verdicts present but none reconciled (ok=null everywhere — wrong
    key column / unshared spool) must NOT exit 0: a CI gate would go
    green with zero rows audited."""
    bench = {
        "metric": "m",
        "value": 1.0,
        "audit": {
            "ok": None,
            "mismatch_epochs": [],
            "epochs": [
                {"epoch": 0, "ok": None, "detail": "no records"},
                {"epoch": 1, "ok": None, "detail": "no records"},
            ],
        },
    }
    path = str(tmp_path / "bench.json")
    with open(path, "w") as f:
        json.dump(bench, f)
    rc = audit_report.main(["--bench", path])
    captured = capsys.readouterr()
    assert rc == 3
    assert "zero coverage" in captured.err


def test_usage_error_exit_code(audit_report, capsys):
    rc = audit_report.main([])
    assert rc == 2
    assert "need at least one" in capsys.readouterr().err
