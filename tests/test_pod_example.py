"""Smoke test of the pod trainer example's full simulated flow.

``examples/train_dlrm_pod.py --simulate-pod 2`` runs the real multi-
controller path on local CPU processes: ``jax.distributed`` rendezvous,
cluster head + DCN joiner, nonce-scoped address exchange, global-array
batch assembly, and the per-step all-ranks-have-a-batch lockstep gate
(reference analog: the Horovod example's multi-worker run,
``examples/horovod/ray_torch_shuffle.py:319-344``)."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_simulated_pod_trains(tmp_path):
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "examples", "train_dlrm_pod.py"),
            "--simulate-pod",
            "2",
            "--num-rows",
            "20000",
            "--batch-size",
            "2048",
            "--epochs",
            "2",
            "--rendezvous-dir",
            str(tmp_path / "rdv"),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=str(tmp_path),
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    # Both ranks must complete both epochs with a finite loss.
    for rank in (0, 1):
        assert f"[pod] rank {rank}: epoch 1 done" in out, out[-4000:]
    assert "loss nan" not in out, out[-4000:]
