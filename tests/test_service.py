"""Multi-job shuffle-service tests (ISSUE 15).

The service contract, proven end to end:

* two concurrent ``shuffle()`` jobs in one session each deliver
  exactly-once with per-job STRICT audit verdicts, and each job's
  ``delivered_seq`` digests are bit-identical to a solo same-seed
  service-OFF run (isolation by construction, and zero-overhead-off's
  "digests unchanged" in one stroke);
* two jobs using the SAME logical batch-queue name coexist (the
  job-id-suffix fix for the latent named-actor race);
* a crashed reducer in one job recovers without touching the other
  job's epochs (chaos leg, strict audit on both);
* fair-share dispatch interleaves queued tasks across jobs by
  weighted share (deterministic unit on a fake pool);
* epoch admission keys on the capacity ledger's shm fraction, bounded,
  with the no-window/sole-tenant progress guarantees;
* content-identity decode-cache sharing makes a second job over the
  same files cache-hot from its first epoch;
* ``RSDL_SERVICE`` unset: the service module is never imported
  (fresh-interpreter subprocess).

Function-scoped runtimes where faults are armed (schedules parse once
per worker process — the PR 3 lesson).
"""

import collections
import os
import subprocess
import sys
import threading

import pytest

from ray_shuffling_data_loader_tpu import runtime
from ray_shuffling_data_loader_tpu.batch_queue import BatchQueue
from ray_shuffling_data_loader_tpu.data_generation import generate_file
from ray_shuffling_data_loader_tpu.runtime import faults, service
from ray_shuffling_data_loader_tpu.shuffle import (
    BatchConsumer,
    live_status,
    protected_epochs,
    shuffle,
)
from ray_shuffling_data_loader_tpu.telemetry import audit as _audit
from ray_shuffling_data_loader_tpu.telemetry import metrics as _metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NUM_FILES = 4
ROWS_PER_FILE = 400
TOTAL_ROWS = NUM_FILES * ROWS_PER_FILE
EPOCHS = 2


@pytest.fixture(scope="module")
def svc_files(tmp_path_factory):
    """Small Parquet dataset written IN-PROCESS (no worker pool): the
    per-test runtimes below must spawn their pools after the service /
    fault env is armed."""
    data_dir = tmp_path_factory.mktemp("service-data")
    files = []
    for i in range(NUM_FILES):
        fname, _ = generate_file(
            i, i * ROWS_PER_FILE, ROWS_PER_FILE, 1, str(data_dir)
        )
        files.append(fname)
    return files


@pytest.fixture
def svc_env(monkeypatch, tmp_path):
    """Arm the service plane (+ audit strict + metrics, optionally a
    fault schedule), then bring up a fresh runtime whose workers
    inherit everything via the environment."""
    started = []

    def arm(faults_spec=None, seed: int = 0, extra_env=None,
            audit: bool = True):
        spool = tmp_path / "audit-spool"
        spool.mkdir(exist_ok=True)
        monkeypatch.setenv("RSDL_SERVICE", "auto")
        if audit:
            monkeypatch.setenv("RSDL_AUDIT", "1")
            monkeypatch.setenv("RSDL_AUDIT_STRICT", "1")
            monkeypatch.setenv("RSDL_AUDIT_DIR", str(spool))
        monkeypatch.setenv("RSDL_METRICS", "1")
        if faults_spec:
            monkeypatch.setenv("RSDL_FAULTS", faults_spec)
            monkeypatch.setenv("RSDL_FAULTS_SEED", str(seed))
        elif faults_spec == "":
            # Explicitly fault-free: for tests asserting SCHEDULE
            # choices (a recovered cache publisher legitimately
            # degrades an epoch to the materialized path — correct,
            # but not what a schedule assertion wants to see).
            monkeypatch.delenv("RSDL_FAULTS", raising=False)
        # else None: any ambient schedule (the CI service lane's
        # low-prob xN-capped one) rides into the spawned workers —
        # recovery is exactly-once, so digests must not notice.
        for k, v in (extra_env or {}).items():
            monkeypatch.setenv(k, v)
        _audit.refresh_from_env()
        _metrics.refresh_from_env()
        _metrics.registry.clear()
        faults.refresh_from_env()
        ctx = runtime.init(num_workers=2)
        started.append(ctx)
        return ctx

    yield arm
    runtime.shutdown()
    service.reset_state()
    monkeypatch.undo()
    _audit.reset()
    _audit.refresh_from_env()
    _metrics.refresh_from_env()
    faults.refresh_from_env()


class CollectingConsumer(BatchConsumer):
    def __init__(self):
        self.keys = collections.defaultdict(list)
        self.done = collections.defaultdict(bool)

    def consume(self, rank, epoch, batches):
        store = runtime.get_context().store
        for ref in batches:
            cb = store.get_columns(ref)
            self.keys[(epoch, rank)].extend(cb["key"].tolist())
            store.free(ref)

    def producer_done(self, rank, epoch):
        self.done[(epoch, rank)] = True

    def wait_until_ready(self, epoch):
        pass

    def wait_until_all_epochs_done(self):
        pass


def _assert_exactly_once(consumer, epoch):
    assert consumer.done[(epoch, 0)]
    assert sorted(consumer.keys[(epoch, 0)]) == list(range(TOTAL_ROWS))


def _run_job(name, files, seed, results, errors, **kw):
    job = service.register_job(name=name)
    try:
        with service.job_context(job):
            consumer = CollectingConsumer()
            shuffle(
                files, consumer, num_epochs=EPOCHS, num_reducers=4,
                num_trainers=1, seed=seed, **kw,
            )
            results[name] = (job, consumer)
    except BaseException as exc:  # surfaced by the test
        errors[name] = exc
    finally:
        service.end_job(job)


# ---------------------------------------------------------------------------
# Units: mode, scoping, fair share, admission
# ---------------------------------------------------------------------------


def test_mode_parsing(monkeypatch):
    monkeypatch.delenv("RSDL_SERVICE", raising=False)
    assert service.mode() == "off" and not service.enabled()
    monkeypatch.setenv("RSDL_SERVICE", "off")
    assert not service.enabled()
    monkeypatch.setenv("RSDL_SERVICE", "auto")
    assert service.enabled() and service.mode() == "auto"


def test_scoped_name(monkeypatch):
    monkeypatch.setenv("RSDL_SERVICE", "auto")
    assert service.current_job() is None
    assert service.scoped_name("Q") == "Q"  # no ambient job: identity
    job = service.Job("j-1-0", "j", 1.0)
    with service.job_context(job):
        scoped = service.scoped_name("Q")
        assert scoped == "Q--j-1-0"
        # Idempotent: an already-scoped name never double-suffixes.
        assert service.scoped_name(scoped) == scoped
    assert service.scoped_name("Q") == "Q"  # context restored


class _FakeFuture:
    """Inner-future stand-in with manual completion."""

    def __init__(self, tag):
        self.tag = tag
        self._event = threading.Event()
        self._waiters = []
        self._lock = threading.Lock()

    def complete(self):
        with self._lock:
            self._event.set()
            waiters, self._waiters = self._waiters, []
        for w in waiters:
            w.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        assert self._event.wait(timeout)
        return self.tag

    def _add_waiter(self, event):
        with self._lock:
            if self._event.is_set():
                event.set()
            else:
                self._waiters.append(event)

    def _remove_waiter(self, event):
        with self._lock:
            if event in self._waiters:
                self._waiters.remove(event)


class _FakePool:
    width = 2

    def __init__(self):
        self.order = []

    def submit(self, fn, *args, **kwargs):
        fut = _FakeFuture(fn)
        self.order.append(fn)
        return fut

    def submit_local_to(self, refs, fn, *args, **kwargs):
        return self.submit(fn, *args, **kwargs)


def test_fair_share_interleaves_jobs(monkeypatch):
    """Two REGISTERED jobs, width 2: job A's flood is capped at the
    pool width, and the first slot a completion frees goes to job B
    (smaller virtual time / fewer in flight) instead of B waiting
    behind A's whole backlog (FIFO starvation)."""
    monkeypatch.setenv("RSDL_SERVICE", "auto")
    pool = _FakePool()
    sched = service.FairShareScheduler(pool)
    job_a = service.register_job(name="A")
    job_b = service.register_job(name="B")
    try:
        with service.job_context(job_a):
            futs_a = [sched.submit(f"a{i}") for i in range(4)]
        # Two running jobs exist: the cap applies from the very first
        # submissions -- only `width` of A's tasks reach the pool.
        assert pool.order == ["a0", "a1"]
        with service.job_context(job_b):
            futs_b = [sched.submit(f"b{i}") for i in range(2)]
        assert pool.order == ["a0", "a1"]
        # One completion frees one slot: B wins it (vtime tie, fewer
        # in flight) -- no starvation behind A's backlog.
        next(
            inner
            for inner, _j, _p in list(sched._released)
            if inner.tag == "a0"
        ).complete()
        pause = threading.Event()
        for _ in range(100):
            if len(pool.order) > 2:
                break
            pause.wait(0.05)
        assert pool.order[2] == "b0", pool.order
        # Drain everything so the watcher resolves every proxy.
        for _ in range(200):
            for inner, _j, _p in list(sched._released):
                inner.complete()
            if all(f.done() for f in futs_a + futs_b):
                break
            pause.wait(0.05)
        assert all(f.done() for f in futs_a + futs_b)
        assert sorted(pool.order) == sorted(
            [f"a{i}" for i in range(4)] + [f"b{i}" for i in range(2)]
        )
    finally:
        sched.stop()
        service.end_job(job_a)
        service.end_job(job_b)


def test_fair_share_sole_tenant_floods(monkeypatch):
    """One job alone gets the service-off behavior: every task goes
    straight to the pool, no cap, no dispatcher deferral."""
    monkeypatch.setenv("RSDL_SERVICE", "auto")
    pool = _FakePool()
    sched = service.FairShareScheduler(pool)
    job = service.register_job(name="S")
    try:
        with service.job_context(job):
            futs = [sched.submit(f"s{i}") for i in range(5)]
        assert pool.order == [f"s{i}" for i in range(5)]
        for inner, _job, _proxy in list(sched._released):
            inner.complete()
        pause = threading.Event()
        for _ in range(100):
            if all(f.done() for f in futs):
                break
            pause.wait(0.05)
        assert all(f.done() for f in futs)
    finally:
        sched.stop()
        service.end_job(job)


def test_admission_progress_guarantees(monkeypatch):
    """No window in flight, or a sole tenant => admitted immediately;
    under pressure with two active jobs the wait is bounded by the
    timeout knob."""
    monkeypatch.setenv("RSDL_SERVICE", "auto")
    monkeypatch.setenv("RSDL_METRICS", "1")
    monkeypatch.setenv("RSDL_SERVICE_ADMIT_TIMEOUT_S", "0.4")
    _metrics.refresh_from_env()
    from ray_shuffling_data_loader_tpu.telemetry import capacity

    monkeypatch.setattr(
        capacity, "view", lambda *a, **k: {"shm_used_frac": 0.99}
    )
    job_a = service.register_job(name="adm-a")
    try:
        # Sole tenant: admitted even over the watermark.
        assert service.admit_epoch(job_a, 0, in_flight=2) == 0.0
        job_b = service.register_job(name="adm-b")
        try:
            # No window in flight: progress guarantee.
            assert service.admit_epoch(job_a, 0, in_flight=0) == 0.0
            # In flight + pressure + a second tenant: bounded wait.
            waited = service.admit_epoch(job_a, 1, in_flight=1)
            assert 0.3 <= waited <= 2.0
            monkeypatch.setattr(
                capacity, "view", lambda *a, **k: {"shm_used_frac": 0.1}
            )
            assert service.admit_epoch(job_a, 2, in_flight=1) < 0.3
        finally:
            service.end_job(job_b)
    finally:
        service.end_job(job_a)
        _metrics.refresh_from_env()


# ---------------------------------------------------------------------------
# Regression: two same-name jobs coexist (the named-actor race)
# ---------------------------------------------------------------------------


def test_two_same_name_queues_coexist(svc_env):
    """Two jobs creating a batch queue under the SAME logical name get
    two distinct actors (job-id suffix), and each queue carries only
    its own job's items — before ISSUE 15 the second spawn raced the
    first on one registry record."""
    svc_env(audit=False)
    job_a = service.register_job(name="qa")
    job_b = service.register_job(name="qb")
    try:
        with service.job_context(job_a):
            qa = BatchQueue(1, 1, 1, name="svc-queue")
            qa.ready()
        with service.job_context(job_b):
            qb = BatchQueue(1, 1, 1, name="svc-queue")
            qb.ready()
        assert qa.actor.address != qb.actor.address
        qa.new_epoch(0)
        qb.new_epoch(0)
        qa.put_nowait(0, 0, "from-a")
        qb.put_nowait(0, 0, "from-b")
        assert qa.get(0, 0, timeout=5) == "from-a"
        assert qb.get(0, 0, timeout=5) == "from-b"
        # Connecting under job A's context resolves A's actor.
        with service.job_context(job_a):
            handle = runtime.connect_actor("svc-queue")
            assert handle.address == qa.actor.address
        qa.shutdown(force=True)
        qb.shutdown(force=True)
    finally:
        service.end_job(job_a)
        service.end_job(job_b)


# ---------------------------------------------------------------------------
# Two-job concurrency: strict audit + digest-identical to solo runs
# ---------------------------------------------------------------------------


def test_two_jobs_concurrent_audit_isolated(svc_env, svc_files, monkeypatch):
    """The ISSUE 15 acceptance core: two concurrent jobs (same files,
    different seeds) each pass STRICT per-job audit, deliver
    exactly-once, and their per-epoch ``delivered_seq`` digests are
    BIT-IDENTICAL to solo same-seed runs with the service plane off —
    isolation by construction, and the zero-overhead "digests
    unchanged" criterion in the same breath."""
    # Solo reference runs, service OFF.
    monkeypatch.delenv("RSDL_SERVICE", raising=False)
    spool = os.path.join(os.path.dirname(svc_files[0]), "solo-spool")
    monkeypatch.setenv("RSDL_AUDIT", "1")
    monkeypatch.setenv("RSDL_AUDIT_STRICT", "1")
    monkeypatch.setenv("RSDL_AUDIT_DIR", spool)
    _audit.refresh_from_env()
    runtime.init(num_workers=2)
    solo_seq = {}
    for name, seed in (("ja", 7), ("jb", 9)):
        consumer = CollectingConsumer()
        shuffle(
            svc_files, consumer, num_epochs=EPOCHS, num_reducers=4,
            num_trainers=1, seed=seed,
        )
        verdicts = _audit.reconcile(range(EPOCHS))
        assert all(v["ok"] for v in verdicts)
        solo_seq[name] = [v["delivered_seq"] for v in verdicts]
        for e in range(EPOCHS):
            _assert_exactly_once(consumer, e)
    runtime.shutdown()
    _audit.reset(clear_spool=True)

    # Concurrent runs, service ON (fresh runtime; workers inherit env).
    svc_env()
    results, errors = {}, {}
    threads = [
        threading.Thread(
            target=_run_job,
            args=("ja", svc_files, 7, results, errors),
        ),
        threading.Thread(
            target=_run_job,
            args=("jb", svc_files, 9, results, errors),
        ),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors, errors
    assert set(results) == {"ja", "jb"}
    for name in ("ja", "jb"):
        job, consumer = results[name]
        for e in range(EPOCHS):
            _assert_exactly_once(consumer, e)
        verdicts = _audit.reconcile(range(EPOCHS), job=job.job_id)
        assert [v["ok"] for v in verdicts] == [True] * EPOCHS
        assert [
            v["delivered_seq"] for v in verdicts
        ] == solo_seq[name], f"{name}: concurrent digests != solo"
    # The two jobs' streams are genuinely different (different seeds):
    # identical digests across jobs would mean the filter is broken.
    assert solo_seq["ja"] != solo_seq["jb"]


def test_two_jobs_status_and_fence(svc_env, svc_files):
    """While two jobs run, /status's shuffle view carries both jobs and
    the eviction fence is the union of their windows; after both end,
    nothing stays fenced."""
    svc_env(audit=False)
    gate = threading.Event()

    class GatedConsumer(CollectingConsumer):
        def wait_until_ready(self, epoch):
            if epoch > 0:
                gate.wait(timeout=60)

    seen = {}

    def run(name, seed):
        job = service.register_job(name=name)
        try:
            with service.job_context(job):
                consumer = GatedConsumer()
                shuffle(
                    svc_files, consumer, num_epochs=EPOCHS,
                    num_reducers=4, num_trainers=1, seed=seed,
                )
                seen[name] = consumer
        finally:
            service.end_job(job)

    threads = [
        threading.Thread(target=run, args=("sa", 3)),
        threading.Thread(target=run, args=("sb", 4)),
    ]
    for t in threads:
        t.start()
    # Both jobs hold epoch 1 at the gate; epoch 0 flows.
    for _ in range(200):
        st = live_status()
        jobs = st.get("jobs") or {}
        if len(jobs) >= 2 and st.get("running"):
            break
        threading.Event().wait(0.05)
    st = live_status()
    assert len(st.get("jobs") or {}) >= 2
    assert st["running"]
    assert protected_epochs() <= {0, 1}
    gate.set()
    for t in threads:
        t.join(timeout=120)
    assert set(seen) == {"sa", "sb"}
    for consumer in seen.values():
        for e in range(EPOCHS):
            _assert_exactly_once(consumer, e)
    assert protected_epochs() == set()


# ---------------------------------------------------------------------------
# Chaos: one job's reducer crashes; the other job is unaffected
# ---------------------------------------------------------------------------


def test_chaos_reducer_crash_isolated(svc_env, svc_files):
    """A capped crash schedule kills the first reduce attempts (either
    job may be hit): the struck job recovers via the stage budget, the
    neighbor never notices, and BOTH end with strict per-job audit
    ok=true and exactly-once delivery."""
    svc_env("task.reduce/task:crash-exit:1x2", seed=23)
    results, errors = {}, {}
    threads = [
        threading.Thread(
            target=_run_job,
            args=("ca", svc_files, 11, results, errors),
        ),
        threading.Thread(
            target=_run_job,
            args=("cb", svc_files, 13, results, errors),
        ),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    assert not errors, errors
    for name in ("ca", "cb"):
        job, consumer = results[name]
        for e in range(EPOCHS):
            _assert_exactly_once(consumer, e)
        verdicts = _audit.reconcile(range(EPOCHS), job=job.job_id)
        assert [v["ok"] for v in verdicts] == [True] * EPOCHS


# ---------------------------------------------------------------------------
# Cross-job hot-dataset sharing
# ---------------------------------------------------------------------------


def test_cross_job_cache_hot(svc_env, svc_files):
    """Job 2 over the same files rides job 1's decoded segments from
    its FIRST epoch (index schedule at epoch 0 — the Parquet decode is
    skipped entirely), while claims fence the segments and release at
    job end. Explicitly fault-free: a recovered crashed publisher
    legitimately degrades an epoch's schedule, which is not what this
    assertion is about."""
    svc_env(faults_spec="", audit=False)
    service.cache_registry_clear()
    log1, log2 = [], []
    job1 = service.register_job(name="warm")
    with service.job_context(job1):
        c1 = CollectingConsumer()
        shuffle(
            svc_files, c1, num_epochs=EPOCHS, num_reducers=4,
            num_trainers=1, seed=7, cache_decoded=True,
            schedule_log=log1,
        )
    assert dict(log1)[0] == "mapreduce"
    assert dict(log1)[1] == "index"
    # Claims held by the live job fence the segments.
    assert service.claimed_cache_ids()
    job2 = service.register_job(name="rider")
    with service.job_context(job2):
        c2 = CollectingConsumer()
        shuffle(
            svc_files, c2, num_epochs=1, num_reducers=4,
            num_trainers=1, seed=7, cache_decoded=True,
            schedule_log=log2,
        )
    assert dict(log2)[0] == "index", (
        "job 2 should be cache-hot from epoch 0"
    )
    # Same seed => identical stream, via the shared segments.
    assert c2.keys[(0, 0)] == c1.keys[(0, 0)]
    service.end_job(job1)
    service.end_job(job2)
    # Both jobs ended: every claim is released.
    assert service.claimed_cache_ids() == set()


def test_dead_job_claims_do_not_fence(svc_env, svc_files):
    """A SIGKILLed driver never runs end_job: its on-disk record stays
    ``running`` forever, but its claims must NOT fence cache segments
    from the evictor — liveness is record + pid-alive, and a dead pid
    retires the claim."""
    svc_env(faults_spec="", audit=False)
    import json as _json

    ctx = runtime.get_context()
    jobs_dir = os.path.join(ctx.runtime_dir, "service", "jobs")
    os.makedirs(jobs_dir, exist_ok=True)
    # Fabricate a crashed driver's record: running, dead pid.
    dead = {
        "job_id": "ghost-999999-0", "name": "ghost", "weight": 1.0,
        "pid": 999999, "created_ts": 0.0, "ended_ts": None,
        "running": True,
    }
    with open(os.path.join(jobs_dir, "ghost-999999-0.json"), "w") as f:
        _json.dump(dead, f)
    service.cache_registry_clear()
    store = runtime.get_context().store
    import numpy as np

    ref = store.put_columns({"key": np.arange(10, dtype=np.int64)})
    key = service.cache_key(svc_files[0], None, False)
    service.cache_publish(key, ref, job=None)
    with service._registry_locked() as data:
        data[key]["claims"] = {"ghost-999999-0": 0.0}
    assert service.claimed_cache_ids() == set(), (
        "a dead job's claims must not fence segments"
    )
    # A LIVE job's claim (this process) does fence.
    job = service.register_job(name="fence")
    try:
        service.claim_cache(key, job)
        assert ref.object_id in service.claimed_cache_ids()
    finally:
        service.end_job(job)
    assert service.claimed_cache_ids() == set()
    store.free(ref)
    # Cross-process multi-tenancy: the ghost is dead, so this process's
    # sole job must still count as a sole tenant for admission.
    job2 = service.register_job(name="solo-count")
    try:
        assert service.live_jobs_count() == 1
    finally:
        service.end_job(job2)


def test_audit_reconcile_folds_resume_chain(monkeypatch, tmp_path):
    """Job ids change across restarts: a journaled service resume
    reconciles with the whole chain of attempt ids (threaded through
    the journal identity's ``audit_jobs``), so the preempted attempt's
    carried records fold instead of reporting a false mismatch."""
    import numpy as np
    from ray_shuffling_data_loader_tpu import telemetry

    monkeypatch.setenv("RSDL_AUDIT", "1")
    monkeypatch.setenv("RSDL_AUDIT_DIR", str(tmp_path / "spool"))
    _audit.refresh_from_env()
    _audit.reset(clear_spool=True)
    keys = np.arange(100, dtype=np.int64)
    try:
        # Attempt 1 (old id): map + reduce before the "preemption".
        with telemetry.context(job="t-1-0"):
            _audit.record_map(0, 0, {"key": keys})
            _audit.record_reduce(0, 0, {"key": keys})
        # Attempt 2 (new id): delivery of the same epoch.
        with telemetry.context(job="t-2-0"):
            _audit.record_deliver(0, 0, 0, {"key": keys}, offset=0)
        # Newest id alone: the old attempt's worker records are
        # invisible -> incomplete, not ok.
        (v_new,) = _audit.reconcile([0], job="t-2-0")
        assert v_new["ok"] is not True
        # The chain folds both attempts: exactly-once reconciles, and
        # the verdict carries the NEWEST attempt's id.
        (v_chain,) = _audit.reconcile([0], job=["t-1-0", "t-2-0"])
        assert v_chain["ok"] is True
        assert v_chain["job"] == "t-2-0"
        assert v_chain["rows_mapped"] == 100
        assert v_chain["rows_delivered"] == 100
    finally:
        _audit.reset(clear_spool=True)
        _audit.refresh_from_env()


def test_cache_key_content_identity(svc_files, tmp_path):
    """The content key fingerprints path + size + mtime + projection +
    narrowing: same file/same shape agree across calls; a different
    projection (or file) never collides."""
    k1 = service.cache_key(svc_files[0], None, False)
    assert k1 == service.cache_key(svc_files[0], None, False)
    assert k1 != service.cache_key(svc_files[0], ["key"], False)
    assert k1 != service.cache_key(svc_files[0], None, True)
    assert k1 != service.cache_key(svc_files[1], None, False)


# ---------------------------------------------------------------------------
# Fleet observability (ISSUE 16): per-job SLO isolation + /jobs
# ---------------------------------------------------------------------------


def test_two_jobs_slo_fire_and_resolve_isolated(
    svc_env, svc_files, monkeypatch
):
    """The ISSUE 16 SLO acceptance: job A's delivery stalls behind a
    gated consumer — the (short-window) per-job ``producer_stalled``
    instance fires for A ALONE (``alert.active{job,rule}`` gauge up,
    job-stamped fire event), job B never leaves ok, and releasing the
    gate resolves A's instance — with both jobs still ending
    strict-audit ok=true."""
    import json as _json
    import time as _time

    from ray_shuffling_data_loader_tpu.telemetry import events as _events
    from ray_shuffling_data_loader_tpu.telemetry import slo as _slo
    from ray_shuffling_data_loader_tpu.telemetry import (
        timeseries as _timeseries,
    )

    # Shorten producer_stalled so a held consumer gate (not a 30 s
    # production outage) trips it: all-zero delivered-bytes rate across
    # 8 s, held 2 s. Job B's continuous delivery keeps a non-zero
    # sample inside any 8 s window, so B cannot trip it.
    monkeypatch.setenv("RSDL_SLO_RULES", _json.dumps([
        {"name": "producer_stalled", "kind": "rate",
         "metric": "shuffle.reduce_rows",
         "per_job": True, "per_job_metric": "service.delivered_bytes",
         "op": "==", "value": 0.0, "window_s": 8.0, "for_s": 2.0,
         "only_in_flight": True, "severity": "page"},
    ]))
    svc_env()
    _events.reset()
    _timeseries.reset()
    _slo.reset()
    gate = threading.Event()

    class GatedConsumer(CollectingConsumer):
        def wait_until_ready(self, epoch):
            if epoch > 0:
                assert gate.wait(timeout=180)

    results, errors, ids = {}, {}, {}

    def run(name, seed, consumer_cls):
        job = service.register_job(name=name)
        ids[name] = job.job_id
        try:
            with service.job_context(job):
                consumer = consumer_cls()
                shuffle(
                    svc_files, consumer, num_epochs=EPOCHS,
                    num_reducers=4, num_trainers=1, seed=seed,
                )
                results[name] = (job, consumer)
        except BaseException as exc:
            errors[name] = exc
        finally:
            service.end_job(job)

    threads = [
        threading.Thread(target=run, args=("sa", 7, GatedConsumer)),
        threading.Thread(target=run, args=("sb", 9, CollectingConsumer)),
    ]
    for t in threads:
        t.start()
    try:
        # Drive the sampler tick by hand (sample, then evaluate — the
        # engine reads the fresh ring) until A's instance fires.
        saw_both = False
        fired_key = None
        deadline = _time.time() + 150
        while _time.time() < deadline and fired_key is None:
            _timeseries.sample_now()
            out = _slo.evaluate()
            saw_both = saw_both or set(ids.values()) <= set(out["jobs"])
            for active in out["active"]:
                if active.startswith("producer_stalled|"):
                    fired_key = active
            _time.sleep(0.2)
        assert fired_key == f"producer_stalled|{ids['sa']}", (
            fired_key, ids,
        )
        assert saw_both, "both tenants never live in one tick"
        snap = _metrics.registry.snapshot()
        assert snap[
            f"alert.active{{job={ids['sa']},rule=producer_stalled}}"
        ] == 1.0
        assert _slo.active_alerts_by_job().get(ids["sa"]) == [
            "producer_stalled"
        ]
        assert ids["sb"] not in _slo.active_alerts_by_job()
        # Release the gate: delivery resumes and A's instance resolves
        # (rate recovers, or the trial drains — either clears it).
        gate.set()
        resolved = False
        deadline = _time.time() + 150
        while _time.time() < deadline and not resolved:
            _timeseries.sample_now()
            out = _slo.evaluate()
            resolved = fired_key not in out["active"]
            _time.sleep(0.2)
        assert resolved, "producer_stalled|sa never resolved"
    finally:
        gate.set()
        for t in threads:
            t.join(timeout=240)
    assert not errors, errors
    assert set(results) == {"sa", "sb"}
    for name in ("sa", "sb"):
        job, consumer = results[name]
        for e in range(EPOCHS):
            _assert_exactly_once(consumer, e)
        verdicts = _audit.reconcile(range(EPOCHS), job=job.job_id)
        assert [v["ok"] for v in verdicts] == [True] * EPOCHS
    fired = [r for r in _events.load() if r.get("kind") == "alert.fired"]
    assert any(
        r.get("job") == ids["sa"] and r.get("rule") == "producer_stalled"
        for r in fired
    ), fired
    assert not [r for r in fired if r.get("job") == ids["sb"]], (
        "job B fired an alert"
    )
    assert [
        r for r in _events.load()
        if r.get("kind") == "alert.resolved" and r.get("job") == ids["sa"]
    ]
    counts = _slo.fired_counts()
    assert counts.get(f"producer_stalled|{ids['sa']}", 0) >= 1
    assert not [k for k in counts if ids["sb"] in k]


def test_jobs_endpoint_lists_both_tenants(svc_env, svc_files, monkeypatch):
    """``/jobs`` (ISSUE 16): with two tenants gated mid-flight the
    fleet view serves one row each — service identity, trial shape,
    and the default alert/claims columns — and ``/status`` carries the
    running set in its ``fleet`` section; after both end neither row
    shows running."""
    import json as _json
    import urllib.request

    from ray_shuffling_data_loader_tpu.telemetry import obs_server

    svc_env(audit=False)
    port = obs_server.start(0)
    # shuffle() registers its live-trial provider only when the obs
    # endpoint is configured; point the gate at the bound port.
    monkeypatch.setenv("RSDL_OBS_PORT", str(port))

    def get(path):
        url = f"http://127.0.0.1:{port}{path}"
        with urllib.request.urlopen(url, timeout=10) as resp:
            return _json.loads(resp.read().decode())

    gate = threading.Event()

    class GatedConsumer(CollectingConsumer):
        def wait_until_ready(self, epoch):
            if epoch > 0:
                assert gate.wait(timeout=180)

    results, errors, ids = {}, {}, {}

    def run(name, seed):
        job = service.register_job(name=name)
        ids[name] = job.job_id
        try:
            with service.job_context(job):
                consumer = GatedConsumer()
                shuffle(
                    svc_files, consumer, num_epochs=EPOCHS,
                    num_reducers=4, num_trainers=1, seed=seed,
                )
                results[name] = consumer
        except BaseException as exc:
            errors[name] = exc
        finally:
            service.end_job(job)

    threads = [
        threading.Thread(target=run, args=("fa", 3)),
        threading.Thread(target=run, args=("fb", 4)),
    ]
    for t in threads:
        t.start()
    try:
        import time as _time

        body = None
        deadline = _time.time() + 120
        while _time.time() < deadline:
            body = get("/jobs")
            rows = {
                r["job_id"]: r for r in body["jobs"] if r.get("running")
            }
            if set(ids.values()) <= set(rows) and all(
                rows[j].get("num_epochs") for j in ids.values()
            ):
                break
            _time.sleep(0.2)
        assert body and body["service_mode"] == "auto"
        rows = {r["job_id"]: r for r in body["jobs"]}
        assert set(ids.values()) <= set(rows), (ids, list(rows))
        for name, jid in ids.items():
            row = rows[jid]
            assert row["name"] == name
            assert row["running"] is True
            assert row["pid"] == os.getpid()
            assert row["weight"] == 1.0
            assert row["num_epochs"] == EPOCHS
            assert row["num_reducers"] == 4
            assert row["active_alerts"] == []
            assert "cache_claims" in row
        # /status mirrors the running set in its fleet section.
        fleet = get("/status").get("fleet") or {}
        running_ids = {r["job_id"] for r in fleet.get("running", [])}
        assert set(ids.values()) <= running_ids, fleet
    finally:
        gate.set()
        for t in threads:
            t.join(timeout=240)
        obs_server.stop()
    assert not errors, errors
    assert set(results) == {"fa", "fb"}


# ---------------------------------------------------------------------------
# Zero-overhead off
# ---------------------------------------------------------------------------


def test_service_off_never_imports_plane():
    """RSDL_SERVICE unset: a fresh interpreter exercising the gate
    points (runtime init + scheduler property, batch queue, the shuffle
    module, shared-cache parser) never loads the service module and
    starts no fair-share thread."""
    code = """
import os, sys, threading
for k in list(os.environ):
    if k.startswith("RSDL_"):
        del os.environ[k]
os.environ["JAX_PLATFORMS"] = "cpu"
import importlib
from ray_shuffling_data_loader_tpu import runtime
# importlib, not `import ... as`: the package exports a `shuffle`
# FUNCTION attribute that shadows the module on as-binding.
sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
ctx = runtime.init(num_workers=1)
_ = ctx.scheduler  # the wrap point
assert not sh.shared_decode_cache_enabled()
from ray_shuffling_data_loader_tpu.batch_queue import BatchQueue
q = BatchQueue(1, 1, 1, name="zq")
q.ready()
q.shutdown(force=True)
runtime.shutdown()
assert "ray_shuffling_data_loader_tpu.runtime.service" not in sys.modules, (
    "service plane imported on a service-off run")
assert not [t for t in threading.enumerate() if "fair-share" in t.name]
print("ZERO_OVERHEAD_OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=180,
        env={**os.environ, "PYTHONPATH": _REPO},
        cwd=_REPO,
    )
    assert out.returncode == 0, out.stderr
    assert "ZERO_OVERHEAD_OK" in out.stdout
